"""NBK6xx — interprocedural sharding-flow analysis.

The failure class this targets arrives with the partition-rule
ingestion plane (ROADMAP #3): once PartitionSpecs are data — built per
catalog column by rule trees — a spec disagreement between producer
and consumer no longer fails loudly.  jax inserts the reshard for you:
an implicit all_to_all (or worse, an all_gather) hiding inside a jit
boundary, invisible until a profile shows the FFT's collective budget
spent twice.  Likewise a mesh-sized output with replicated
``out_specs`` is a silent P-way all_gather plus P copies of a buffer
the memory plan priced once.

**The spec model.**  A PartitionSpec is abstracted to a tuple of
per-dimension entries: an axis name (string), a tuple of axis names,
``None`` (replicated), or :data:`UNRESOLVED` when the expression
cannot be pinned statically.  Specs are read from literal ``P(...)`` /
``PartitionSpec(...)`` calls — through module/project constants
(``AXIS``), single-assignment local names, and tuple-unpack bindings
(``in1, out1 = P(...), P(...)``).  Anything dynamic (comprehensions,
concatenation, parameters) stays :data:`UNRESOLVED` and the rules are
silent about it: like the rest of nbkl, false negatives are preferred
to noise.

Spec facts then flow interprocedurally: every ``shard_map``
construction becomes a :class:`Boundary` (wrapped function, in/out
specs, mesh axes); calling a boundary binds its ``out_specs`` to the
result name; function return summaries run to fixpoint over the
:class:`~nbodykit_tpu.lint.callgraph.Project` graph so a helper that
returns a sharded field carries its spec to call sites in other
modules.  Mesh-sizedness is delegated to the NBK5xx value model
(sizes.py ``_OWN`` taint) — a chunk-sized scalar crossing with a
different spec is cheap and not flagged.

The mesh itself resolves through the repo's constructor table
(:data:`MESH_CONSTRUCTOR_AXES` — ``cpu_mesh()``/``tpu_mesh()`` bind
``('dev',)``, ``pencil_mesh()`` binds ``('x', 'y')``) or a literal
``Mesh(..., axis_names=...)`` / ``jax.make_mesh`` call.

Rules
-----
NBK601  mesh-sized value crossing a shard_map boundary with a spec
        that disagrees with the spec it was produced under — an
        implicit reshard (hidden all_to_all/all_gather).
NBK602  mesh-sized, non-reduced output bound to replicated
        ``out_specs`` — a hidden P-way all_gather and P replicas.
NBK603  literal ``in_specs``/``out_specs`` whose arity disagrees with
        the wrapped function's signature / return tuple.
NBK604  collective inside a shard_map body naming an axis the
        resolved mesh does not define.

``--shard-report`` renders every discovered boundary with its
resolved specs and mesh axes (the sharding analogue of sizes.py's
``--memory-report``).
"""

import ast
import collections

from .scopes import SHARD_MAP_NAMES
from . import sizes as _sizes


class _Unresolved(object):
    """Singleton spec entry for statically-unresolvable expressions."""
    __slots__ = ()

    def __repr__(self):
        return '?'


UNRESOLVED = _Unresolved()

#: repo mesh-constructor tails -> the axis names they bind
#: (parallel/runtime.py: the slab constructors all share AXIS='dev',
#: pencil_mesh binds (AXIS_X, AXIS_Y) = ('x', 'y'))
MESH_CONSTRUCTOR_AXES = {
    'world_mesh': ('dev',),
    'single_device_mesh': ('dev',),
    'cpu_mesh': ('dev',),
    'tpu_mesh': ('dev',),
    'pencil_mesh': ('x', 'y'),
}

#: collectives that REDUCE over the mesh axis — a replicated out_spec
#: on their result is the correct contract, not a hidden gather
_REDUCING_COLLECTIVES = frozenset({
    'psum', 'pmean', 'pmax', 'pmin', 'psum_scatter'})

Boundary = collections.namedtuple('Boundary', [
    'ctx', 'call', 'fn', 'in_specs', 'in_tuple',
    'out_specs', 'out_tuple', 'mesh_axes'])


# ---------------------------------------------------------------------------
# spec / mesh parsing


def _binding(ctx, name, at):
    """The unique expression assigned to ``name`` in the scope chain
    of ``at`` (including one tuple-unpack level), or None when the
    name is unbound, rebound, or bound dynamically."""
    for scope in ctx.scope_chain(at):
        hits = []
        for node in ast.walk(scope):
            if not isinstance(node, ast.Assign):
                continue
            if ctx.enclosing_scope(node) is not scope:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    hits.append(node.value)
                elif isinstance(t, (ast.Tuple, ast.List)) and \
                        isinstance(node.value, (ast.Tuple, ast.List)) \
                        and len(t.elts) == len(node.value.elts):
                    for te, ve in zip(t.elts, node.value.elts):
                        if isinstance(te, ast.Name) and te.id == name:
                            hits.append(ve)
        if hits:
            return hits[0] if len(hits) == 1 else None
    return None


def _parse_spec(ctx, call):
    """A literal ``P(...)``/``PartitionSpec(...)`` call -> entry
    tuple, or None when the call is not a spec constructor."""
    if not isinstance(call, ast.Call):
        return None
    q = ctx.qual(call.func) or ''
    if q.rsplit('.', 1)[-1] not in ('P', 'PartitionSpec'):
        return None
    out = []
    for a in call.args:
        out.append(_spec_entry(ctx, a))
    return tuple(out)


def _spec_entry(ctx, node):
    if isinstance(node, ast.Constant) and node.value is None:
        return None
    if isinstance(node, (ast.Tuple, ast.List)):
        members = []
        for e in node.elts:
            s = ctx.const_str(e)
            if s is None:
                return UNRESOLVED
            members.append(s)
        return tuple(members)
    s = ctx.const_str(node)
    return s if s is not None else UNRESOLVED


def _single_spec(ctx, node, at, depth=0):
    """One spec tuple for an expression, following Name bindings."""
    if depth > 3 or node is None:
        return None
    spec = _parse_spec(ctx, node)
    if spec is not None:
        return spec
    if isinstance(node, ast.Name):
        b = _binding(ctx, node.id, at)
        if b is not None:
            return _single_spec(ctx, b, b, depth + 1)
    return None


def _specs_arg(ctx, node, at):
    """An ``in_specs``/``out_specs`` keyword value ->
    ``(list of spec-or-None, is_literal_tuple)``; ``(None, False)``
    when nothing resolves."""
    if node is None:
        return None, False
    if isinstance(node, (ast.Tuple, ast.List)):
        return [_single_spec(ctx, e, at) for e in node.elts], True
    spec = _single_spec(ctx, node, at)
    if spec is not None:
        return [spec], False
    if isinstance(node, ast.Name):
        b = _binding(ctx, node.id, at)
        if isinstance(b, (ast.Tuple, ast.List)):
            return [_single_spec(ctx, e, b) for e in b.elts], True
    return None, False


def _axis_strs(ctx, node):
    """frozenset of axis-name strings, or None when any token fails
    to resolve."""
    if node is None:
        return None
    toks = ctx.axis_tokens(node)
    if not toks or any(k != 'str' for k, _ in toks):
        return None
    return frozenset(v for _, v in toks)


def mesh_axes_of(ctx, node, at, depth=0):
    """Axis names a ``mesh=`` expression binds, or None: the repo
    constructor table, literal ``Mesh``/``make_mesh`` calls, and Name
    bindings thereto.  Parameters / attributes stay unresolved."""
    if depth > 3 or node is None:
        return None
    if isinstance(node, ast.Call):
        q = ctx.call_name(node) or ''
        tail = q.rsplit('.', 1)[-1]
        if tail in MESH_CONSTRUCTOR_AXES:
            return frozenset(MESH_CONSTRUCTOR_AXES[tail])
        if tail in ('Mesh', 'make_mesh'):
            ax = node.args[1] if len(node.args) >= 2 else None
            for kw in node.keywords:
                if kw.arg == 'axis_names':
                    ax = kw.value
            return _axis_strs(ctx, ax)
        return None
    if isinstance(node, ast.Name):
        b = _binding(ctx, node.id, at)
        if b is not None:
            return mesh_axes_of(ctx, b, b, depth + 1)
    return None


def _wrapped_fn(ctx, call):
    """The function a shard_map call wraps: a direct Lambda or a Name
    resolving to a def — anything else (builder calls, attributes)
    stays None."""
    if not call.args:
        return None
    a = call.args[0]
    if isinstance(a, ast.Lambda):
        return a
    if isinstance(a, ast.Name):
        return ctx._resolve_def(a, call)
    return None


def _boundaries(ctx):
    """{id(call): Boundary} for every shard_map construction in the
    module."""
    out = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not ctx.matches(ctx.call_name(node), SHARD_MAP_NAMES,
                           {'shard_map'}):
            continue
        ins = outs = None
        in_t = out_t = False
        mesh = None
        for kw in node.keywords:
            if kw.arg == 'in_specs':
                ins, in_t = _specs_arg(ctx, kw.value, node)
            elif kw.arg == 'out_specs':
                outs, out_t = _specs_arg(ctx, kw.value, node)
            elif kw.arg == 'mesh':
                mesh = mesh_axes_of(ctx, kw.value, node)
        out[id(node)] = Boundary(ctx, node, _wrapped_fn(ctx, node),
                                 ins, in_t, outs, out_t, mesh)
    return out


# ---------------------------------------------------------------------------
# spec helpers


def _resolved(spec):
    return spec is not None and UNRESOLVED not in spec


def _norm(spec):
    """Strip trailing replicated dims: P('dev') == P('dev', None)."""
    spec = tuple(spec)
    while spec and spec[-1] is None:
        spec = spec[:-1]
    return spec


def _spec_axes(spec):
    """Axis-name strings a spec shards over."""
    out = set()
    for e in spec or ():
        if isinstance(e, str):
            out.add(e)
        elif isinstance(e, tuple):
            out.update(e)
    return out


def render_spec(spec):
    if spec is None:
        return '?'
    return 'P(%s)' % ','.join(
        '?' if e is UNRESOLVED
        else 'None' if e is None
        else '+'.join(e) if isinstance(e, tuple)
        else e
        for e in spec)


def _params_of(fn):
    """Positional parameter names, or None when *args makes the arity
    open."""
    a = fn.args
    if a.vararg is not None:
        return None
    return [p.arg for p in a.posonlyargs + a.args if p.arg != 'self']


def _return_exprs(ctx, fn):
    """The function's return expressions (Lambda body counts)."""
    if isinstance(fn, ast.Lambda):
        return [fn.body]
    return [n.value for n in ast.walk(fn)
            if isinstance(n, ast.Return) and n.value is not None
            and ctx.enclosing_function(n) is fn]


def _return_elements(ctx, fn, nspecs, out_tuple):
    """Per-out_spec return expressions, or None when the return
    structure cannot be matched to the specs."""
    exprs = _return_exprs(ctx, fn)
    if len(exprs) != 1:
        return None
    e = exprs[0]
    if not out_tuple:
        return [e] if nspecs == 1 else None
    if isinstance(e, (ast.Tuple, ast.List)) and len(e.elts) == nspecs:
        return list(e.elts)
    return None


def _is_reduced(ctx, expr):
    """Is the expression a reduction — a reducing collective or a
    REDUCER_TAILS call (possibly re-cast with .astype)?"""
    e = expr
    for _ in range(2):
        if not isinstance(e, ast.Call):
            return False
        tail = _sizes._call_tail(ctx, e)
        if tail in _REDUCING_COLLECTIVES or \
                tail in _sizes.REDUCER_TAILS:
            return True
        if tail == 'astype' and isinstance(e.func, ast.Attribute):
            e = e.func.value
            continue
        return False
    return False


# ---------------------------------------------------------------------------
# the interprocedural analysis


class _Analysis(object):
    """Project-wide boundary table plus a returns-spec fixpoint."""

    def __init__(self, project):
        self.project = project
        self.bounds = {}        # id(call) -> Boundary
        self.by_ctx = {}        # id(ctx) -> [Boundary]
        for ctx in project.contexts:
            bs = _boundaries(ctx)
            self.bounds.update(bs)
            self.by_ctx[id(ctx)] = list(bs.values())
        # (id(scope), name) -> Boundary for `s1 = shard_map(...)` /
        # `j1 = jit(s1)` wrapper assignments; two passes so a jit of
        # a later-defined name still resolves
        self.wrappers = {id(ctx): {} for ctx in project.contexts}
        for _ in range(2):
            for ctx in project.contexts:
                table = self.wrappers[id(ctx)]
                for node in ast.walk(ctx.tree):
                    if not isinstance(node, ast.Assign):
                        continue
                    b = self._construction(ctx, node.value)
                    if b is None:
                        continue
                    scope = ctx.enclosing_scope(node)
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            table[(id(scope), t.id)] = b
        # returns-spec summaries to fixpoint
        self.returns_spec = {}
        for _ in range(4):
            changed = False
            for ctx, fn in project.functions():
                spec = self._fn_return_spec(ctx, fn)
                if spec != self.returns_spec.get(id(fn)):
                    self.returns_spec[id(fn)] = spec
                    changed = True
            if not changed:
                break

    # -- boundary resolution -----------------------------------------------

    def _construction(self, ctx, node, depth=0):
        """Boundary when ``node`` constructs (a wrapper around) a
        shard_map: ``shard_map(...)``, ``jit(shard_map(...))``,
        ``instrumented_jit(s1)``."""
        if depth > 3 or not isinstance(node, ast.Call):
            return None
        b = self.bounds.get(id(node))
        if b is not None:
            return b
        q = ctx.call_name(node) or ''
        tail = q.rsplit('.', 1)[-1]
        if tail in ('jit', 'pjit', 'pmap', 'instrumented_jit',
                    'partial', 'checkpoint', 'remat') and node.args:
            inner = node.args[0]
            if isinstance(inner, ast.Call):
                return self._construction(ctx, inner, depth + 1)
            if isinstance(inner, ast.Name):
                return self._named(ctx, inner.id, node)
        return None

    def _named(self, ctx, name, at):
        table = self.wrappers.get(id(ctx), {})
        for scope in ctx.scope_chain(at):
            b = table.get((id(scope), name))
            if b is not None:
                return b
        return None

    def boundary_of_call(self, ctx, call):
        """The Boundary a call site invokes, or None —
        ``s1(x)`` through a wrapper name, or the immediate
        ``jax.shard_map(...)(x)`` form."""
        f = call.func
        if isinstance(f, ast.Call):
            return self._construction(ctx, f)
        if isinstance(f, ast.Name):
            return self._named(ctx, f.id, call)
        return None

    # -- spec dataflow -----------------------------------------------------

    def spec_facts(self, ctx, fn):
        """{name: spec} for names in ``fn`` bound to results of
        boundary calls (with resolved single/tuple out_specs) or of
        functions whose returns-spec summary is known."""
        facts = {}
        for _ in range(2):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                if ctx.enclosing_function(node) is not fn:
                    continue
                specs = self._result_specs(ctx, node.value)
                if not specs:
                    continue
                tgt = node.targets[0] if len(node.targets) == 1 \
                    else None
                if isinstance(tgt, ast.Name) and len(specs) == 1 and \
                        specs[0] is not None:
                    facts[tgt.id] = specs[0]
                elif isinstance(tgt, (ast.Tuple, ast.List)) and \
                        len(tgt.elts) == len(specs):
                    for te, s in zip(tgt.elts, specs):
                        if isinstance(te, ast.Name) and s is not None:
                            facts[te.id] = s
        return facts

    def _result_specs(self, ctx, value):
        """Out-spec list of a call expression's result, or None."""
        if not isinstance(value, ast.Call):
            return None
        b = self.boundary_of_call(ctx, value)
        if b is not None and b.out_specs:
            return b.out_specs
        tgt = self.project.resolve_call(ctx, value)
        if tgt is not None and tgt.ref is not None:
            spec = self.returns_spec.get(id(tgt.ref.node))
            if spec is not None:
                return [spec]
        return None

    def _fn_return_spec(self, ctx, fn):
        """Spec of the function's (single) return value, or None."""
        exprs = _return_exprs(ctx, fn)
        if len(exprs) != 1:
            return None
        e = exprs[0]
        if isinstance(e, ast.Name):
            return self.spec_facts(ctx, fn).get(e.id)
        specs = self._result_specs(ctx, e)
        if specs and len(specs) == 1:
            return specs[0]
        return None


def analysis_for(project):
    cached = getattr(project, '_shard_analysis', None)
    if cached is None:
        cached = _Analysis(project)
        project._shard_analysis = cached
    return cached


def _project_of(ctx):
    project = getattr(ctx, 'project', None)
    if project is None:
        from .callgraph import single_project
        project = single_project(ctx)
    return project


# ---------------------------------------------------------------------------
# rule entry points (wrapped into Findings by rules.py)


def find_reshards(ctx):
    """NBK601 raw findings: (call, name, spec_have, spec_want)."""
    project = _project_of(ctx)
    an = analysis_for(project)
    mem = _sizes.analysis_for(project)
    out = []
    for fn in ctx.functions:
        facts = an.spec_facts(ctx, fn)
        if not facts:
            continue
        fm = mem.func_mem(fn)
        for call in project.calls_in(ctx, fn):
            b = an.boundary_of_call(ctx, call)
            if b is None or not b.in_specs:
                continue
            for i, arg in enumerate(call.args):
                if not isinstance(arg, ast.Name):
                    continue
                have = facts.get(arg.id)
                if have is None or not _resolved(have):
                    continue
                want = None
                if b.in_tuple and i < len(b.in_specs):
                    want = b.in_specs[i]
                elif not b.in_tuple and len(call.args) == 1:
                    want = b.in_specs[0]
                if want is None or not _resolved(want):
                    continue
                if _norm(have) == _norm(want):
                    continue
                if fm is None or \
                        _sizes._OWN not in fm.expr_labels(arg):
                    continue        # only mesh-sized crossings matter
                out.append((call, arg.id, have, want))
    return out


def find_replicated_outputs(ctx):
    """NBK602 raw findings: (call, out_index, label) — mesh-sized,
    non-reduced outputs bound to fully-replicated out_specs."""
    project = _project_of(ctx)
    an = analysis_for(project)
    mem = _sizes.analysis_for(project)
    out = []
    for b in an.by_ctx.get(id(ctx), []):
        if b.fn is None or not b.out_specs:
            continue
        fm = mem.func_mem(b.fn)
        if fm is None:
            continue
        params = _params_of(b.fn)
        sharded_params = set()
        if b.in_specs and params is not None:
            ins = b.in_specs
            if not b.in_tuple and len(ins) == 1 and len(params) > 1:
                ins = ins * len(params)
            for p, s in zip(params, ins):
                if s is not None and _spec_axes(s):
                    sharded_params.add(p)
        rets = _return_elements(ctx, b.fn, len(b.out_specs),
                                b.out_tuple)
        if rets is None:
            continue
        for idx, (spec, rexpr) in enumerate(zip(b.out_specs, rets)):
            if not _resolved(spec) or _spec_axes(spec):
                continue        # unresolved, or sharded somewhere
            if _is_reduced(ctx, rexpr):
                continue        # psum/sum output: replication is real
            labels = fm.expr_labels(rexpr)
            if _sizes._OWN in labels or (labels & sharded_params):
                out.append((b.call, idx, render_spec(spec)))
    return out


def find_arity_mismatches(ctx):
    """NBK603 raw findings: (call, kind, nspecs, nactual)."""
    project = _project_of(ctx)
    an = analysis_for(project)
    out = []
    for b in an.by_ctx.get(id(ctx), []):
        if b.fn is None:
            continue
        params = _params_of(b.fn)
        if b.in_tuple and b.in_specs is not None and \
                params is not None and len(b.in_specs) != len(params):
            out.append((b.call, 'in_specs', len(b.in_specs),
                        len(params)))
        if b.out_tuple and b.out_specs is not None:
            exprs = _return_exprs(ctx, b.fn)
            if len(exprs) == 1 and \
                    isinstance(exprs[0], (ast.Tuple, ast.List)) and \
                    len(exprs[0].elts) != len(b.out_specs):
                out.append((b.call, 'out_specs', len(b.out_specs),
                            len(exprs[0].elts)))
    return out


def find_foreign_axis_collectives(ctx):
    """NBK604 raw findings: (collective call, axis names, mesh axes)
    — a collective naming an axis the resolved mesh does not
    define."""
    project = _project_of(ctx)
    an = analysis_for(project)
    seen = set()
    out = []
    for b in an.by_ctx.get(id(ctx), []):
        if b.mesh_axes is None or b.fn is None:
            continue
        for node in ast.walk(b.fn):
            if not ctx.is_collective(node) or id(node) in seen:
                continue
            axis = ctx.collective_axis_arg(node)
            names = _axis_strs(ctx, axis)
            if not names or names & b.mesh_axes:
                continue
            seen.add(id(node))
            out.append((node, names, b.mesh_axes))
    return out


# ---------------------------------------------------------------------------
# the shard report


def shard_report(project):
    """Rows for the ``--shard-report`` table: every shard_map
    boundary with its resolved wrapped function, mesh axes and
    specs."""
    an = analysis_for(project)
    rows = []
    for ctx in project.contexts:
        for b in an.by_ctx.get(id(ctx), []):
            if b.fn is None:
                label = '?'
            elif isinstance(b.fn, ast.Lambda):
                label = '<lambda:%d>' % b.fn.lineno
            else:
                label = b.fn.name
            rows.append({
                'path': getattr(ctx, 'canonical', ctx.path),
                'line': b.call.lineno,
                'function': label,
                'mesh_axes': sorted(b.mesh_axes)
                if b.mesh_axes is not None else None,
                'in_specs': [render_spec(s) for s in b.in_specs]
                if b.in_specs is not None else None,
                'out_specs': [render_spec(s) for s in b.out_specs]
                if b.out_specs is not None else None,
            })
    rows.sort(key=lambda r: (r['path'], r['line']))
    return {'rows': rows}


def render_shard_report(report):
    """The report as aligned text."""
    rows = report['rows']
    out = ['== nbkl shard report: %d shard_map boundar%s =='
           % (len(rows), 'y' if len(rows) == 1 else 'ies')]
    if not rows:
        out.append('no shard_map boundaries found')
        return '\n'.join(out) + '\n'

    def specs(v):
        return '?' if v is None else '(%s)' % ', '.join(v)

    fw = max(len('%s:%d' % (r['path'], r['line'])) for r in rows)
    gw = max(len(r['function']) for r in rows)
    for r in rows:
        mesh = ','.join(r['mesh_axes']) \
            if r['mesh_axes'] is not None else '?'
        out.append('  %-*s  %-*s  mesh=%-5s  in=%s -> out=%s'
                   % (fw, '%s:%d' % (r['path'], r['line']),
                      gw, r['function'], mesh,
                      specs(r['in_specs']), specs(r['out_specs'])))
    unresolved = sum(1 for r in rows
                     if r['in_specs'] is None or
                     r['out_specs'] is None or r['mesh_axes'] is None)
    out.append('%d boundar%s, %d with unresolved specs/mesh '
               '(silent for the NBK6xx rules)'
               % (len(rows), 'y' if len(rows) == 1 else 'ies',
                  unresolved))
    return '\n'.join(out) + '\n'
