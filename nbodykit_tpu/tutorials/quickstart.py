"""Executable quickstart: every docs/EXAMPLES.md flow at test scale.

Run it directly (``python -m nbodykit_tpu.tutorials.quickstart``) or
through ``run_all(Nmesh=..., BoxSize=...)``; each step returns its
headline result
so the test suite can execute the whole cookbook
(tests/test_misc_algorithms.py::test_quickstart_cookbook).
"""

import numpy as np
import jax.numpy as jnp


def run_all(Nmesh=32, BoxSize=200.0, verbose=False):
    """Run the cookbook end-to-end at the given scale; returns a dict
    of step name -> summary value (all finite when healthy)."""
    from ..lab import (UniformCatalog, LogNormalCatalog, LinearPower,
                       Planck15, FFTPower, FFTCorr, FKPCatalog,
                       ConvolvedFFTPower, FFTRecon, FOF,
                       SimulationBox2PCF, Zheng07Model, BigFileCatalog,
                       TaskManager, CorrelationFunction, HalofitPower)
    import os
    import shutil
    import tempfile

    out = {}

    def log(step, value):
        out[step] = value
        if verbose:
            print('%-22s %s' % (step, value))

    # 1. lognormal mock -> P(k, mu) + poles
    Plin = LinearPower(Planck15, redshift=0.55,
                       transfer='EisensteinHu')
    cat = LogNormalCatalog(Plin=Plin, nbar=3e-4, BoxSize=BoxSize,
                           Nmesh=Nmesh, bias=2.0, seed=42)
    mesh = cat.to_mesh(resampler='tsc', compensated=True,
                       interlaced=True)
    r = FFTPower(mesh, mode='2d', Nmu=5, poles=[0, 2])
    log('fftpower_p0', float(np.real(
        np.asarray(r.poles['power_0'])[2])))

    # 2. save / load round trip
    tmp = tempfile.mkdtemp(prefix='nbkit_quickstart_')
    fn = os.path.join(tmp, 'power.json')
    r.save(fn)
    r2 = FFTPower.load(fn)
    log('roundtrip_ok', bool(np.allclose(
        np.asarray(r.power['power'].real),
        np.asarray(r2.power['power'].real), equal_nan=True)))

    # 3. FKP survey multipoles
    data = UniformCatalog(nbar=3e-4, BoxSize=BoxSize, seed=1)
    randoms = UniformCatalog(nbar=3e-3, BoxSize=BoxSize, seed=2)
    for c in (data, randoms):
        c['NZ'] = 3e-4 * jnp.ones(c.size)
    rf = ConvolvedFFTPower(FKPCatalog(data, randoms).to_mesh(
        Nmesh=Nmesh, resampler='tsc'), poles=[0, 2], dk=0.05)
    log('fkp_p0', float(np.real(np.asarray(
        rf.poles['power_0'])).mean()))

    # 4. FOF halos -> HOD population
    fof = FOF(cat, linking_length=0.2, nmin=8)
    halos = fof.to_halos(particle_mass=1e13, cosmo=Planck15,
                         redshift=0.55)
    log('n_halos', int(halos.size))
    if halos.size:
        hod = halos.populate(Zheng07Model, seed=42, logMmin=12.5)
        log('n_hod', int(hod.size))

    # 5. correlation functions
    xi = FFTCorr(cat.to_mesh(Nmesh=Nmesh, compensated=True),
                 mode='1d')
    log('fftcorr_xi0', float(np.real(
        np.asarray(xi.corr['corr'])[1])))
    edges = np.linspace(5.0, 25.0, 6)
    tpcf = SimulationBox2PCF('1d', cat, edges)
    log('tpcf_xi0', float(np.asarray(tpcf.corr['corr'])[0]))

    # 6. BAO reconstruction
    recon = FFTRecon(data=cat, ran=randoms, Nmesh=Nmesh, bias=2.0,
                     R=15.0, scheme='LGS')
    log('recon_mean', float(np.asarray(
        recon.compute(mode='real').value).mean()))

    # 7. IO round trip through bigfile
    path = os.path.join(tmp, 'cat.bigfile')
    cat.save(path, columns=['Position', 'Velocity'])
    back = BigFileCatalog(path)
    log('bigfile_ok', bool(back.size == cat.size))

    # 8. task farming over seeds
    with TaskManager(cpus_per_task=1) as tm:
        p0s = []
        for seed in tm.iterate([9, 10]):
            c = UniformCatalog(nbar=2e-3, BoxSize=100.0, seed=seed)
            rr = FFTPower(c.to_mesh(Nmesh=16), mode='1d')
            p0s.append(float(np.real(
                np.asarray(rr.power['power'])[1])))
    log('farmed', len(p0s))

    # 9. cosmology
    log('sigma8', float(Planck15.sigma8))
    log('halofit_ok', float(HalofitPower(Planck15, 0.5)(0.1)) > 0)
    log('xi_of_r', float(CorrelationFunction(Plin)(80.0)))

    shutil.rmtree(tmp, ignore_errors=True)
    return out


if __name__ == '__main__':
    run_all(verbose=True)
