"""Tutorial data helpers.

Reference: ``nbodykit/tutorials/`` — DemoHaloCatalog downloads sample
halo catalogs (halos.py:5) via a data mirror (wget.py:61-198). This
environment has no network egress, so the demo catalog is *generated*:
a reproducible FOF-halo-like catalog from a seeded lognormal mock,
exposing the same columns (Position, Velocity, Mass).
"""

import numpy as np

from ..source.catalog.array import ArrayCatalog


def DemoHaloCatalog(simname='fake', halo_finder='fof', redshift=0.5,
                    seed=42, comm=None):
    """A reproducible demo halo catalog (generated, not downloaded)."""
    rng = np.random.RandomState(seed)
    BoxSize = 250.0
    N = 5000
    # mass function ~ power law tail
    mass = 10 ** rng.uniform(12.0, 15.0, N)
    pos = rng.uniform(0, BoxSize, size=(N, 3))
    vel = rng.normal(0, 300.0, size=(N, 3))
    cat = ArrayCatalog({'Position': pos, 'Velocity': vel,
                        'Mass': mass}, comm=comm, BoxSize=BoxSize)
    cat.attrs.update(simname=simname, halo_finder=halo_finder,
                     redshift=redshift, seed=seed)
    return cat


# ---------------------------------------------------------------------------
# offline example-data store (reference: tutorials/wget.py:61-198 —
# download_example_data/available_examples pull files from a NERSC data
# mirror; this environment has no egress, so the same API *generates*
# the example files locally, deterministically, in the formats the
# framework reads)

def _write_csv(path, rng):
    data = rng.uniform(0, 1000.0, size=(1024, 7))
    np.savetxt(path, data, fmt='%.7e',
               header='ra dec z x y z_cart w', comments='# ')


def _write_hdf(path, rng):
    import h5py
    with h5py.File(path, 'w') as ff:
        g = ff.create_group('Data')
        g.create_dataset('Position',
                         data=rng.uniform(0, 250.0, size=(2048, 3)))
        g.create_dataset('Velocity',
                         data=rng.normal(0, 300.0, size=(2048, 3)))
        g.create_dataset('Mass', data=10 ** rng.uniform(12, 15, 2048))


def _write_bigfile(path, rng):
    from ..io.bigfile import BigFileWriter
    w = BigFileWriter(path)
    w.write('Position', rng.uniform(0, 250.0, size=(2048, 3))
            .astype('f4'))
    w.write('Velocity', rng.normal(0, 300.0, size=(2048, 3))
            .astype('f4'))
    w.write_attrs('Header', {'BoxSize': [250.0] * 3, 'Nmesh': 64})


def _write_binary(path, rng):
    with open(path, 'wb') as ff:
        rng.uniform(0, 250.0, size=(1024, 3)).astype('f4').tofile(ff)
        rng.normal(0, 300.0, size=(1024, 3)).astype('f4').tofile(ff)


def _write_fits(path, rng):
    # the native writer lives next to the native parser (io/fits.py)
    # so the two conventions evolve together
    from ..io.fits import write_bintable
    n = 512
    write_bintable(path, [
        ('RA', rng.uniform(0, 360.0, n)),
        ('DEC', rng.uniform(-10.0, 10.0, n)),
        ('Z', rng.uniform(0.3, 0.7, n))])


_EXAMPLES = {
    'csv-example.txt': _write_csv,
    'hdf-example.hdf5': _write_hdf,
    'bigfile-example': _write_bigfile,
    'binary-example.bin': _write_binary,
    'fits-example.fits': _write_fits,
}


def available_examples():
    """The example data files this offline store can materialize
    (reference analog: tutorials/wget.py:128 lists the NERSC mirror)."""
    return sorted(_EXAMPLES)


def download_example_data(filenames, download_dirname=None, seed=2024):
    """Materialize example data files locally (reference analog:
    tutorials/wget.py:152 downloads them; zero-egress here, so the
    files are generated deterministically from ``seed`` instead —
    byte-stable across calls, same API).

    Parameters
    ----------
    filenames : str or list of str — names from
        :func:`available_examples`
    download_dirname : optional existing directory (default: cwd)
    """
    import os
    if isinstance(filenames, str):
        filenames = [filenames]
    if download_dirname is not None and not os.path.isdir(
            download_dirname):
        raise ValueError("specified download directory is not valid")
    for filename in filenames:
        if filename not in _EXAMPLES:
            raise ValueError(
                "no such example file '%s'\n\navailable examples "
                "are: %s" % (filename, available_examples()))
        target = filename if download_dirname is None else \
            os.path.join(download_dirname, filename)
        _EXAMPLES[filename](target, np.random.RandomState(seed))
