"""Tutorial data helpers.

Reference: ``nbodykit/tutorials/`` — DemoHaloCatalog downloads sample
halo catalogs (halos.py:5) via a data mirror (wget.py:61-198). This
environment has no network egress, so the demo catalog is *generated*:
a reproducible FOF-halo-like catalog from a seeded lognormal mock,
exposing the same columns (Position, Velocity, Mass).
"""

import numpy as np

from ..source.catalog.array import ArrayCatalog


def DemoHaloCatalog(simname='fake', halo_finder='fof', redshift=0.5,
                    seed=42, comm=None):
    """A reproducible demo halo catalog (generated, not downloaded)."""
    rng = np.random.RandomState(seed)
    BoxSize = 250.0
    N = 5000
    # mass function ~ power law tail
    mass = 10 ** rng.uniform(12.0, 15.0, N)
    pos = rng.uniform(0, BoxSize, size=(N, 3))
    vel = rng.normal(0, 300.0, size=(N, 3))
    cat = ArrayCatalog({'Position': pos, 'Velocity': vel,
                        'Mass': mass}, comm=comm, BoxSize=BoxSize)
    cat.attrs.update(simname=simname, halo_finder=halo_finder,
                     redshift=redshift, seed=seed)
    return cat


def download_example_data(*args, **kwargs):
    raise RuntimeError("this environment has no network egress; demo "
                       "data is generated locally (DemoHaloCatalog)")
