"""TaskManager: task-level parallelism.

Reference: ``nbodykit/batch.py:53`` — splits MPI COMM_WORLD into
fixed-size worker sub-communicators and runs a master-worker loop with
point-to-point tags (:172-267). The TPU equivalent of rank-splitting is
*device sub-meshes*: the available devices are partitioned into groups
of ``cpus_per_task`` and tasks are farmed to the groups CONCURRENTLY —
one worker thread per sub-mesh, each with its own thread-local ambient
:class:`~.parallel.runtime.CurrentMesh`. jax dispatch is asynchronous,
so work launched on disjoint device groups overlaps on hardware just as
the reference's worker groups do across ranks; the thread pool plays
the master role of the reference's READY/DONE tag loop.

API parity: ``with TaskManager(cpus_per_task) as tm:`` then
``tm.iterate(tasks)`` (serial generator on the first sub-mesh) or
``tm.map(func, tasks)`` (concurrent farming, results in task order).
"""

import logging
import queue
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .parallel.runtime import CurrentMesh, use_mesh, AXIS


def split_ranks(N_ranks, N_per, include_all=False):
    """Partition range(N_ranks) into chunks of N_per (reference
    batch.py:8); yields (color, ranks)."""
    available = list(range(N_ranks))
    total = len(available)
    color = 0
    i = 0
    while i < total:
        ranks = available[i:i + N_per]
        yield color, ranks
        color += 1
        i += N_per


class TaskManager(object):
    """Farm tasks to sub-meshes of the device mesh.

    Parameters
    ----------
    cpus_per_task : devices per task group
    use_all_cpus : give every task the whole mesh instead (serial)
    debug : verbose logging
    """

    logger = logging.getLogger('TaskManager')

    def __init__(self, cpus_per_task, comm=None, debug=False,
                 use_all_cpus=False):
        self.cpus_per_task = cpus_per_task
        self.use_all_cpus = use_all_cpus
        if debug:
            self.logger.setLevel(logging.DEBUG)
        self.comm = CurrentMesh.resolve(comm)
        self._ctx = None

    def _sub_meshes(self):
        """Partition the mesh's devices into task groups (the analog of
        reference split_ranks + comm.Split, batch.py:110-151)."""
        from jax.sharding import Mesh
        if self.comm is None or self.use_all_cpus:
            return [self.comm]
        devs = list(np.asarray(self.comm.devices).ravel())
        groups = [devs[i:i + self.cpus_per_task]
                  for i in range(0, len(devs), self.cpus_per_task)]
        # drop a trailing partial group (the reference leaves leftover
        # ranks idle the same way)
        groups = [g for g in groups if len(g) == self.cpus_per_task] \
            or groups[:1]
        return [Mesh(np.array(g), (AXIS,)) for g in groups]

    def __enter__(self):
        self._meshes = self._sub_meshes()
        self._ctx = use_mesh(self._meshes[0])
        self._ctx.__enter__()
        return self

    def __exit__(self, *args):
        if self._ctx is not None:
            self._ctx.__exit__(*args)
            self._ctx = None

    def iterate(self, tasks):
        """Iterate over tasks (reference batch.py:268); the ambient
        mesh inside the loop is the first sub-mesh."""
        for task in tasks:
            yield task

    def map(self, function, tasks):
        """Apply ``function`` to every task, farming tasks over the
        sub-meshes concurrently; results come back in task order
        (reference batch.py:297, whose master-worker loop also
        preserves ordering by index)."""
        tasks = list(tasks)
        meshes = getattr(self, '_meshes', None) or self._sub_meshes()
        if len(meshes) <= 1 or len(tasks) <= 1:
            return [function(t) for t in tasks]

        pool = queue.Queue()
        for m in meshes:
            pool.put(m)

        def run(task):
            mesh = pool.get()
            try:
                with use_mesh(mesh):
                    self.logger.debug("task on sub-mesh %s", mesh)
                    return function(task)
            finally:
                pool.put(mesh)

        with ThreadPoolExecutor(max_workers=len(meshes)) as ex:
            return list(ex.map(run, tasks))

    def is_root(self):
        return True

    def everyone(self):
        from contextlib import contextmanager

        @contextmanager
        def ctx():
            yield
        return ctx()
