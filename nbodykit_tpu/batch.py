"""TaskManager: task-level parallelism.

Reference: ``nbodykit/batch.py:53`` — splits MPI COMM_WORLD into
fixed-size worker sub-communicators and runs a master-worker loop with
point-to-point tags (:172-267). The TPU equivalent of rank-splitting is
*device sub-meshes*: the available devices are split into groups of
``cpus_per_task``, each task runs with its sub-mesh pushed as the
ambient CurrentMesh, and the controller iterates tasks (serially on one
host — multi-host farming rides jax.distributed in a later round).

API parity: ``with TaskManager(cpus_per_task) as tm:`` then
``tm.iterate(tasks)`` / ``tm.map(func, tasks)``.
"""

import logging

import numpy as np

from .parallel.runtime import CurrentMesh, use_mesh, AXIS


def split_ranks(N_ranks, N_per, include_all=False):
    """Partition range(N_ranks) into chunks of N_per (reference
    batch.py:8); yields (color, ranks)."""
    available = list(range(N_ranks))
    total = len(available)
    color = 0
    i = 0
    while i < total:
        ranks = available[i:i + N_per]
        yield color, ranks
        color += 1
        i += N_per


class TaskManager(object):
    """Iterate over tasks, each executed on a sub-mesh of the device
    mesh.

    Parameters
    ----------
    cpus_per_task : devices per task group
    use_all_cpus : give every task the whole mesh instead
    debug : verbose logging
    """

    logger = logging.getLogger('TaskManager')

    def __init__(self, cpus_per_task, comm=None, debug=False,
                 use_all_cpus=False):
        self.cpus_per_task = cpus_per_task
        self.use_all_cpus = use_all_cpus
        if debug:
            self.logger.setLevel(logging.DEBUG)
        self.comm = CurrentMesh.resolve(comm)
        self._ctx = None

    def _sub_mesh(self):
        import jax
        from jax.sharding import Mesh
        if self.comm is None or self.use_all_cpus:
            return self.comm
        devs = list(np.asarray(self.comm.devices).ravel())
        sub = devs[:self.cpus_per_task]
        return Mesh(np.array(sub), (AXIS,))

    def __enter__(self):
        self._ctx = use_mesh(self._sub_mesh())
        self._ctx.__enter__()
        return self

    def __exit__(self, *args):
        if self._ctx is not None:
            self._ctx.__exit__(*args)
            self._ctx = None

    def iterate(self, tasks):
        """Iterate over tasks (reference batch.py:268); the ambient
        mesh inside the loop is the task's sub-mesh."""
        for task in tasks:
            yield task

    def map(self, function, tasks):
        """Apply ``function`` to every task, returning results in order
        (reference batch.py:297)."""
        return [function(task) for task in tasks]

    def is_root(self):
        return True

    def everyone(self):
        from contextlib import contextmanager

        @contextmanager
        def ctx():
            yield
        return ctx()
