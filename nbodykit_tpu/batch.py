"""TaskManager: task-level parallelism.

Reference: ``nbodykit/batch.py:53`` — splits MPI COMM_WORLD into
fixed-size worker sub-communicators and runs a master-worker loop with
point-to-point tags (:172-267). The TPU equivalent of rank-splitting is
*device sub-meshes*: the available devices are partitioned into groups
of ``cpus_per_task`` and tasks are farmed to the groups CONCURRENTLY —
one worker thread per sub-mesh, each with its own thread-local ambient
:class:`~.parallel.runtime.CurrentMesh`. jax dispatch is asynchronous,
so work launched on disjoint device groups overlaps on hardware just as
the reference's worker groups do across ranks; the thread pool plays
the master role of the reference's READY/DONE tag loop.

Multi-host (multi-slice) jobs: after
:func:`~.parallel.runtime.init_distributed` every process sees the
global device list; the groups are then formed along PROCESS
boundaries (each group spans whole hosts) and a process only executes
the tasks owned by its group — the analog of the reference farming
worker sub-communicators across COMM_WORLD (batch.py:110-267). Task
assignment is static round-robin (multi-controller jax has no
cross-process tag channel; the reference's dynamic master-worker
scheduling assumed one). Results are exchanged host-to-host with
``jax.experimental.multihost_utils`` so ``map`` returns the full
task-ordered result list on every process, exactly like the
reference's terminal allgather (batch.py:343-346).

API parity: ``with TaskManager(cpus_per_task) as tm:`` then
``tm.iterate(tasks)`` (serial generator on the first sub-mesh) or
``tm.map(func, tasks)`` (concurrent farming, results in task order).
"""

import logging
import pickle
import queue
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .parallel.runtime import CurrentMesh, use_mesh, AXIS


def split_ranks(N_ranks, N_per, include_all=False):
    """Partition range(N_ranks) into chunks of N_per (reference
    batch.py:8); yields (color, ranks)."""
    available = list(range(N_ranks))
    total = len(available)
    color = 0
    i = 0
    while i < total:
        ranks = available[i:i + N_per]
        yield color, ranks
        color += 1
        i += N_per


class TaskManager(object):
    """Farm tasks to sub-meshes of the device mesh.

    Parameters
    ----------
    cpus_per_task : devices per task group
    use_all_cpus : give every task the whole mesh instead (serial)
    debug : verbose logging
    """

    logger = logging.getLogger('TaskManager')

    def __init__(self, cpus_per_task, comm=None, debug=False,
                 use_all_cpus=False):
        self.cpus_per_task = cpus_per_task
        self.use_all_cpus = use_all_cpus
        if debug:
            self.logger.setLevel(logging.DEBUG)
        self.comm = CurrentMesh.resolve(comm)
        self._ctx = None

    def _sub_meshes(self):
        """Partition the mesh's devices into task groups (the analog of
        reference split_ranks + comm.Split, batch.py:110-151)."""
        from jax.sharding import Mesh
        if self.comm is None or self.use_all_cpus:
            return [self.comm]
        devs = list(np.asarray(self.comm.devices).ravel())
        groups = [devs[i:i + self.cpus_per_task]
                  for i in range(0, len(devs), self.cpus_per_task)]
        # drop a trailing partial group (the reference leaves leftover
        # ranks idle the same way)
        groups = [g for g in groups if len(g) == self.cpus_per_task] \
            or groups[:1]
        return [Mesh(np.array(g), (AXIS,)) for g in groups]

    def sub_meshes(self):
        """The task-group device sub-meshes this manager farms onto
        (single-process form).  Public so long-lived layers on top —
        the serving loop of :mod:`nbodykit_tpu.serve` pins one worker
        thread per sub-mesh — partition devices exactly the way
        :meth:`map` does."""
        return self._sub_meshes()

    # -- multi-host farming -----------------------------------------------

    def _process_groups(self):
        """Partition the job's PROCESSES into task groups of
        ``ceil(cpus_per_task / local_device_count)`` hosts each; every
        group's devices form one sub-mesh spanning whole hosts (a
        process cannot execute a program on a mesh that excludes its
        own devices while including others')."""
        import jax
        from jax.sharding import Mesh
        nproc = jax.process_count()
        ndev_local = max(1, len(jax.local_devices()))
        per = max(1, -(-self.cpus_per_task // ndev_local))
        if self.use_all_cpus:
            per = nproc
        groups = []
        for lo in range(0, nproc - per + 1, per):
            procs = list(range(lo, lo + per))
            devs = [d for d in jax.devices()
                    if getattr(d, 'process_index', 0) in procs]
            groups.append((procs, Mesh(np.array(devs), (AXIS,))))
        if not groups:  # fewer processes than a single group needs
            groups = [(list(range(nproc)),
                       Mesh(np.array(jax.devices()), (AXIS,)))]
        grouped = {p for procs, _ in groups for p in procs}
        idle = sorted(set(range(nproc)) - grouped)
        if idle:
            # same situation the reference's split_ranks warns about:
            # ranks that fit no full group sit out the whole session
            self.logger.warning(
                "%d process(es) %s do not fill a %d-host task group "
                "and will be idle", len(idle), idle, per)
        return groups

    def _my_group(self, groups):
        import jax
        pid = jax.process_index()
        for gi, (procs, mesh) in enumerate(groups):
            if pid in procs:
                return gi, procs, mesh
        return None, [], None  # leftover host: idle worker

    @staticmethod
    def _exchange_results(local):
        """Allgather a {tasknum: result} dict across processes via
        pickled uint8 payloads (the reference's terminal
        ``basecomm.allgather``, batch.py:343-346). Collective: every
        process must call, idle ones with an empty dict."""
        from jax.experimental import multihost_utils

        payload = np.frombuffer(pickle.dumps(local), dtype=np.uint8)
        n = np.array([payload.size], dtype=np.int64)
        sizes = np.asarray(multihost_utils.process_allgather(n)) \
            .reshape(-1)
        cap = int(sizes.max())
        padded = np.zeros(cap, dtype=np.uint8)
        padded[:payload.size] = payload
        gathered = np.asarray(
            multihost_utils.process_allgather(padded, tiled=False))
        gathered = gathered.reshape(len(sizes), cap)
        merged = {}
        for i, size in enumerate(sizes):
            merged.update(pickle.loads(gathered[i, :int(size)]
                                       .tobytes()))
        return merged

    @staticmethod
    def _fetch_to_host(res, mesh):
        """Convert jax.Array leaves of a task result to host numpy.
        Arrays sharded over a multi-host group mesh are first
        replicated ON that mesh (a collective all group processes
        execute in lockstep) — a fully-replicated array is fetchable
        on every host, where one with non-addressable shards is not."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        def fetch(x):
            if not isinstance(x, jax.Array):
                return x
            if not x.is_fully_addressable and not x.is_fully_replicated:
                x = jax.jit(lambda a: a, out_shardings=NamedSharding(
                    mesh, PartitionSpec()))(x)
            return np.asarray(x)
        return jax.tree.map(fetch, res)

    def _map_multihost(self, function, tasks):
        """Static round-robin farming across process groups; results
        allgathered so every process returns the full ordered list."""
        import jax
        groups = getattr(self, '_mh_groups', None) \
            or self._process_groups()
        gi, procs, mesh = self._my_group(groups)
        local = {}
        for i, task in enumerate(tasks):
            if gi is not None and i % len(groups) == gi:
                with use_mesh(mesh):
                    self.logger.debug(
                        "task %d on process group %s", i, procs)
                    res = self._fetch_to_host(function(task), mesh)
                # only the group's first process publishes (results
                # are replicated within a group, reference
                # batch.py:340-341)
                if jax.process_index() == procs[0]:
                    local[i] = res
        merged = self._exchange_results(local)
        missing = [i for i in range(len(tasks)) if i not in merged]
        if missing:
            raise RuntimeError(
                "multi-host task farming lost results for tasks %s"
                % missing)
        return [merged[i] for i in range(len(tasks))]

    def __enter__(self):
        import jax
        if jax.process_count() > 1:
            # multi-host: the ambient mesh is THIS process's group
            # mesh (a process must not enter a mesh excluding its own
            # devices); an idle leftover host keeps its local devices
            self._mh_groups = self._process_groups()
            gi, _procs, mesh = self._my_group(self._mh_groups)
            if mesh is None:
                from jax.sharding import Mesh
                mesh = Mesh(np.array(jax.local_devices()), (AXIS,))
            self._meshes = [mesh]
        else:
            self._mh_groups = None
            self._meshes = self._sub_meshes()
        self._ctx = use_mesh(self._meshes[0])
        self._ctx.__enter__()
        return self

    def __exit__(self, *args):
        if self._ctx is not None:
            self._ctx.__exit__(*args)
            self._ctx = None

    def iterate(self, tasks):
        """Iterate over tasks (reference batch.py:268); the ambient
        mesh inside the loop is the first sub-mesh. In a multi-host
        job each process group sees only its round-robin share, like
        the reference's workers (batch.py:268-295)."""
        import jax
        if jax.process_count() > 1:
            groups = getattr(self, '_mh_groups', None) \
                or self._process_groups()
            gi, _procs, _mesh = self._my_group(groups)
            for i, task in enumerate(tasks):
                if gi is not None and i % len(groups) == gi:
                    yield task
            return
        for task in tasks:
            yield task

    def map(self, function, tasks):
        """Apply ``function`` to every task, farming tasks over the
        sub-meshes concurrently; results come back in task order
        (reference batch.py:297, whose master-worker loop also
        preserves ordering by index)."""
        import jax
        tasks = list(tasks)
        if jax.process_count() > 1:
            return self._map_multihost(function, tasks)
        meshes = getattr(self, '_meshes', None) or self._sub_meshes()
        if len(meshes) <= 1 or len(tasks) <= 1:
            return [function(t) for t in tasks]

        pool = queue.Queue()
        for m in meshes:
            pool.put(m)

        def run(task):
            mesh = pool.get()
            try:
                with use_mesh(mesh):
                    self.logger.debug("task on sub-mesh %s", mesh)
                    return function(task)
            finally:
                pool.put(mesh)

        # submit + collect explicitly (not ex.map): a raising task
        # must surface its ORIGINAL exception and traceback, tagged
        # with the task index, while already-running tasks on the
        # other sub-meshes complete and still-queued ones are
        # cancelled — never a deadlock, never a swallowed error.
        with ThreadPoolExecutor(max_workers=len(meshes)) as ex:
            futures = [ex.submit(run, t) for t in tasks]
            results, first_err = [], None
            for i, fut in enumerate(futures):
                try:
                    results.append(fut.result())
                except BaseException as e:
                    if first_err is None:
                        first_err = (i, e)
                        for later in futures[i + 1:]:
                            later.cancel()
                    results.append(None)
            if first_err is not None:
                i, e = first_err
                self.logger.error("task %d raised %s: %s",
                                  i, type(e).__name__, e)
                e.task_index = i
                raise e
            return results

    def is_root(self):
        return True

    def everyone(self):
        from contextlib import contextmanager

        @contextmanager
        def ctx():
            yield
        return ctx()
