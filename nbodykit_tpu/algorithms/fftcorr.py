"""FFTCorr: correlation function xi(r) in a periodic box via FFT.

Reference: ``nbodykit/algorithms/fftcorr.py:15`` — the same estimator as
FFTPower, transformed back to configuration space (c2r of the 3-D power,
normalized to be dimensionless) and binned in separation.
"""

import logging

import numpy as np

from .fftpower import FFTBase, project_to_basis, _find_unique_edges
from ..binned_statistic import BinnedStatistic


class FFTCorr(FFTBase):
    """xi(r), xi(r,mu) and multipoles xi_ell(r) in a periodic box.

    Parameters mirror :class:`FFTPower` with (dr, rmin, rmax) binning.
    Results in :attr:`corr` / :attr:`poles`.
    """

    logger = logging.getLogger('FFTCorr')

    def __init__(self, first, mode, Nmesh=None, BoxSize=None, second=None,
                 los=[0, 0, 1], Nmu=5, dr=None, rmin=0., rmax=None,
                 poles=[]):
        if mode not in ['1d', '2d']:
            raise ValueError("mode must be '1d' or '2d'")
        if poles is None:
            poles = []
        if np.isscalar(los) or len(los) != 3:
            raise ValueError("line-of-sight must be a 3-vector")

        FFTBase.__init__(self, first, second, Nmesh, BoxSize)

        self.attrs['mode'] = mode
        self.attrs['los'] = los
        self.attrs['Nmu'] = Nmu
        self.attrs['poles'] = poles
        if dr is None:
            dr = self.attrs['BoxSize'].min() / self.attrs['Nmesh'].min()
        self.attrs['dr'] = dr
        self.attrs['rmin'] = rmin
        self.attrs['rmax'] = rmax

        self.corr, self.poles = self.run()
        self.attrs.update(self.corr.attrs)

    def run(self):
        if self.attrs['mode'] == '1d':
            self.attrs['Nmu'] = 1

        y3d, attrs = self._compute_3d_power(self.first, self.second)
        # back to configuration space; L^3 cancels with dk^3 so xi is
        # p3d's inverse transform / V (reference fftcorr.py:154-158)
        xi3d = y3d.c2r()
        xi3d.value = xi3d.value / self.attrs['BoxSize'].prod()

        dr = self.attrs['dr']
        rmin = self.attrs['rmin']
        rmax = self.attrs['rmax']
        if rmax is None:
            rmax = 0.5 * y3d.pm.BoxSize.min() + dr / 2
        if dr > 0:
            redges = np.arange(rmin, rmax, dr)
            rcoords = None
        else:
            # dr=0: one bin per unique lattice separation (reference
            # fftcorr.py:167-171)
            redges, rcoords = _find_unique_edges(y3d.pm, rmax,
                                                 kind='real')

        muedges = np.linspace(0, 1, self.attrs['Nmu'] + 1, endpoint=True)
        edges = [redges, muedges]
        coords = [rcoords, None]
        result, pole_result = project_to_basis(
            xi3d, edges, poles=self.attrs['poles'], los=self.attrs['los'])

        if self.attrs['mode'] == '1d':
            cols = ['r', 'corr', 'modes']
            icols = [0, 2, 3]
            edges = edges[0:1]
            coords = coords[0:1]
        else:
            cols = ['r', 'mu', 'corr', 'modes']
            icols = [0, 1, 2, 3]

        dtype = np.dtype([(name, result[icol].dtype.str)
                          for icol, name in zip(icols, cols)])
        corr = np.squeeze(np.empty(result[0].shape, dtype=dtype))
        for icol, col in zip(icols, cols):
            corr[col][:] = np.squeeze(result[icol])

        poles = None
        if pole_result is not None:
            r, pole_arr, N = pole_result
            cols = ['r'] + ['corr_%d' % l for l in self.attrs['poles']] \
                + ['modes']
            vals = [r] + [p for p in pole_arr] + [N]
            dtype = np.dtype([(name, vals[i].dtype.str)
                              for i, name in enumerate(cols)])
            poles = np.empty(vals[0].shape, dtype=dtype)
            for i, col in enumerate(cols):
                poles[col][:] = vals[i]

        return self._make_datasets(edges, poles, corr, coords, attrs)

    def _make_datasets(self, edges, poles, corr, coords, attrs):
        if self.attrs['mode'] == '1d':
            corr = BinnedStatistic(['r'], edges, corr,
                                   fields_to_sum=['modes'],
                                   coords=coords, **attrs)
        else:
            corr = BinnedStatistic(['r', 'mu'], edges, corr,
                                   fields_to_sum=['modes'],
                                   coords=coords, **attrs)
        if poles is not None:
            poles = BinnedStatistic(['r'], [corr.edges['r']], poles,
                                    fields_to_sum=['modes'],
                                    coords=[corr.coords['r']], **attrs)
        return corr, poles

    def __getstate__(self):
        return dict(corr=self.corr.__getstate__(),
                    poles=self.poles.__getstate__()
                    if self.poles is not None else None,
                    attrs=self.attrs)

    def __setstate__(self, state):
        self.attrs = state['attrs']
        self.corr = BinnedStatistic.from_state(state['corr'])
        self.poles = BinnedStatistic.from_state(state['poles']) \
            if state['poles'] is not None else None
