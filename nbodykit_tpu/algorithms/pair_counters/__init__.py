from .simbox import SimulationBoxPairCount
from .mocksurvey import SurveyDataPairCount

__all__ = ['SimulationBoxPairCount', 'SurveyDataPairCount']
