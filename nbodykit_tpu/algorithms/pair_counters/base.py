"""Shared machinery for the pair-count algorithms.

Reference: ``nbodykit/algorithms/pair_counters/base.py:5`` — result
packaging into BinnedStatistic + persistence.
"""

import json

import numpy as np

from ...binned_statistic import BinnedStatistic
from ...utils import JSONEncoder, JSONDecoder


def package_result(counts, **attrs):
    """Wrap a core.paircount result dict into a BinnedStatistic with
    the reference's dims/variables conventions (mode/edges/Nmu/pimax
    come from the attrs)."""
    mode = attrs['mode']
    edges = np.asarray(attrs['edges'])
    Nmu = attrs.get('Nmu')
    pimax = attrs.get('pimax')
    npairs = np.atleast_1d(counts['npairs'])
    wnpairs = np.atleast_1d(counts['wnpairs'])

    if mode == '1d':
        dims, bin_edges = ['r'], [edges]
    elif mode == '2d':
        dims = ['r', 'mu']
        bin_edges = [edges, np.linspace(0, 1, Nmu + 1)]
    elif mode == 'projected':
        dims = ['rp', 'pi']
        bin_edges = [edges, np.arange(0, int(pimax) + 1)]
    elif mode == 'angular':
        dims, bin_edges = ['theta'], [edges]
    else:
        raise ValueError(mode)

    shape = tuple(len(e) - 1 for e in bin_edges)
    npairs = npairs.reshape(shape)
    wnpairs = wnpairs.reshape(shape)
    data = {'npairs': npairs, 'wnpairs': wnpairs}
    out = BinnedStatistic(dims, bin_edges, data,
                          fields_to_sum=['npairs', 'wnpairs'])
    out.attrs.update(attrs)  # ('edges' collides with the positional)
    return out


class PairCountBase(object):
    """Base for SimulationBoxPairCount / SurveyDataPairCount; holds
    .pairs and JSON persistence (reference base.py:5)."""

    def save(self, output):
        with open(output, 'w') as ff:
            json.dump(self.__getstate__(), ff, cls=JSONEncoder)

    @classmethod
    def load(cls, output, comm=None):
        with open(output, 'r') as ff:
            state = json.load(ff, cls=JSONDecoder)
        self = object.__new__(cls)
        self.__setstate__(state)
        return self

    def __getstate__(self):
        return dict(pairs=self.pairs.__getstate__(), attrs=self.attrs)

    def __setstate__(self, state):
        self.attrs = state['attrs']
        self.pairs = BinnedStatistic.from_state(state['pairs'])
