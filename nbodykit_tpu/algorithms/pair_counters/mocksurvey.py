"""SurveyDataPairCount: pair counts of sky catalogs.

Reference: ``nbodykit/algorithms/pair_counters/mocksurvey.py`` (wrapping
Corrfunc mocks kernels DDsmu_mocks/DDtheta_mocks): positions come as
(ra, dec[, redshift]) converted to Cartesian with a cosmology; counting
is non-periodic in a data-derived bounding box.
"""

import numpy as np

from .base import PairCountBase, package_result
from .core import paircount
from ...utils import as_numpy
from ... import transform


class SurveyDataPairCount(PairCountBase):
    """Count weighted pairs of survey (sky) data.

    Parameters (reference mocksurvey.py): mode in {'1d','2d','angular',
    'projected'}, catalogs with ra/dec(/redshift) columns, edges,
    cosmo (for comoving distances), Nmu, pimax, weight.
    """

    def __init__(self, mode, first, edges, cosmo=None, second=None,
                 Nmu=None, pimax=None, ra='RA', dec='DEC',
                 redshift='Redshift', weight='Weight',
                 show_progress=False):
        if mode not in ('1d', '2d', 'projected', 'angular'):
            raise ValueError("invalid mode %r" % mode)
        self.comm = first.comm
        self.attrs = dict(mode=mode, edges=np.asarray(edges), Nmu=Nmu,
                          pimax=pimax, weight=weight)

        def get_pos(cat):
            if mode == 'angular':
                pos = transform.SkyToUnitSphere(cat[ra], cat[dec])
                return as_numpy(pos)
            if cosmo is None:
                raise ValueError("need a cosmology to convert redshifts "
                                 "to distances")
            pos = transform.SkyToCartesian(cat[ra], cat[dec],
                                           cat[redshift], cosmo)
            return as_numpy(pos)

        pos1 = get_pos(first)
        w1 = as_numpy(first[weight]) if weight in first else None
        if second is None or second is first:
            pos2, w2 = pos1, w1
            is_auto = True
        else:
            pos2 = get_pos(second)
            w2 = as_numpy(second[weight]) if weight in second else None
            is_auto = False

        if mode == 'angular':
            box = np.ones(3)  # unused by the angular path
            counts = paircount(pos1, w1, pos2, w2, box, edges,
                               mode=mode, periodic=False,
                               is_auto=is_auto)
        else:
            # non-periodic bounding box; mu against the pair midpoint
            # direction from the observer (Corrfunc-mocks convention)
            lo = np.minimum(pos1.min(axis=0), pos2.min(axis=0))
            hi = np.maximum(pos1.max(axis=0), pos2.max(axis=0))
            box = (hi - lo) * 1.001 + 1e-3
            counts = paircount(pos1, w1, pos2, w2, box, edges,
                               mode=mode, Nmu=Nmu, pimax=pimax,
                               periodic=False, is_auto=is_auto,
                               grid_origin=lo, pair_los='midpoint')

        W1 = float(np.sum(w1)) if w1 is not None else float(len(pos1))
        W2 = float(np.sum(w2)) if w2 is not None else float(len(pos2))
        if is_auto:
            sumw2 = float(np.sum((w1 if w1 is not None
                                  else np.ones(len(pos1))) ** 2))
            total = W1 * W1 - sumw2
        else:
            total = W1 * W2
        self.attrs.update(total_wnpairs=total, W1=W1, W2=W2,
                          N1=len(pos1), N2=len(pos2), is_auto=is_auto)

        self.pairs = package_result(counts, **self.attrs)
