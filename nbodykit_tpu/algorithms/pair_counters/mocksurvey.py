"""SurveyDataPairCount: pair counts of sky catalogs.

Reference: ``nbodykit/algorithms/pair_counters/mocksurvey.py`` (wrapping
Corrfunc mocks kernels DDsmu_mocks/DDtheta_mocks): positions come as
(ra, dec[, redshift]) converted to Cartesian with a cosmology; counting
is non-periodic in a data-derived bounding box.
"""

import numpy as np

from .base import PairCountBase, package_result
from .core import paircount, paircount_dist, rmax_of
from ...parallel.runtime import mesh_size
from ...utils import as_numpy
from ... import transform


class SurveyDataPairCount(PairCountBase):
    """Count weighted pairs of survey (sky) data.

    Parameters (reference mocksurvey.py): mode in {'1d','2d','angular',
    'projected'}, catalogs with ra/dec(/redshift) columns, edges,
    cosmo (for comoving distances), Nmu, pimax, weight.
    """

    def __init__(self, mode, first, edges, cosmo=None, second=None,
                 Nmu=None, pimax=None, ra='RA', dec='DEC',
                 redshift='Redshift', weight='Weight',
                 show_progress=False):
        if mode not in ('1d', '2d', 'projected', 'angular'):
            raise ValueError("invalid mode %r" % mode)
        if mode == '2d' and Nmu is None:
            raise ValueError("mode='2d' requires Nmu")
        if mode == 'projected' and pimax is None:
            raise ValueError("mode='projected' requires pimax")
        self.comm = first.comm
        self.attrs = dict(mode=mode, edges=np.asarray(edges), Nmu=Nmu,
                          pimax=pimax, weight=weight)

        import jax.numpy as jnp
        nproc = mesh_size(self.comm)
        rmax = rmax_of(mode, edges, pimax)

        def get_pos(cat):
            if mode == 'angular':
                pos = transform.SkyToUnitSphere(cat[ra], cat[dec])
            else:
                if cosmo is None:
                    raise ValueError("need a cosmology to convert "
                                     "redshifts to distances")
                pos = transform.SkyToCartesian(cat[ra], cat[dec],
                                               cat[redshift], cosmo)
            return jnp.asarray(pos)

        pos1 = get_pos(first)
        w1 = jnp.asarray(first[weight]) if weight in first else None
        if second is None or second is first:
            pos2, w2 = pos1, w1
            is_auto = True
        else:
            pos2 = get_pos(second)
            w2 = jnp.asarray(second[weight]) if weight in second \
                else None
            is_auto = False

        if mode == 'angular':
            box = np.ones(3)  # unused by the angular path
            kw = dict(mode=mode, periodic=False, is_auto=is_auto)
            use_dist = nproc > 1 and rmax <= 4.0 / nproc
        else:
            # non-periodic bounding box; mu against the pair midpoint
            # direction from the observer (Corrfunc-mocks convention)
            lo = np.minimum(np.asarray(pos1.min(axis=0)),
                            np.asarray(pos2.min(axis=0)))
            hi = np.maximum(np.asarray(pos1.max(axis=0)),
                            np.asarray(pos2.max(axis=0)))
            box = (hi - lo) * 1.001 + 1e-3
            kw = dict(mode=mode, Nmu=Nmu, pimax=pimax, periodic=False,
                      is_auto=is_auto, grid_origin=lo,
                      pair_los='midpoint')
            use_dist = nproc > 1 and rmax <= box[0] / nproc

        if use_dist:
            counts = paircount_dist(pos1, w1, pos2, w2, box, edges,
                                    self.comm, **kw)
        else:
            p1n = as_numpy(pos1)
            p2n = p1n if pos2 is pos1 else as_numpy(pos2)
            w1n = as_numpy(w1) if w1 is not None else None
            w2n = w1n if w2 is w1 else (
                as_numpy(w2) if w2 is not None else None)
            counts = paircount(p1n, w1n, p2n, w2n, box, edges, **kw)

        W1 = float(np.sum(w1)) if w1 is not None else float(len(pos1))
        W2 = float(np.sum(w2)) if w2 is not None else float(len(pos2))
        if is_auto:
            sumw2 = float(np.sum((w1 if w1 is not None
                                  else np.ones(len(pos1))) ** 2))
            total = W1 * W1 - sumw2
        else:
            total = W1 * W2
        self.attrs.update(total_wnpairs=total, W1=W1, W2=W2,
                          N1=len(pos1), N2=len(pos2), is_auto=is_auto)

        self.pairs = package_result(counts, **self.attrs)
