"""The TPU pair-counting kernel.

Replaces the Corrfunc C/AVX kernels the reference wraps
(nbodykit/algorithms/pair_counters/corrfunc/*; SURVEY.md §2.3): weighted
pair counts binned in r, (r, mu), (rp, pi), or theta.

Built on the shared grid-hash sweep (:class:`...ops.gridhash.GridHash`,
also powering FOF/KDDensity/3PCF): hash the *secondary* set onto cells
of size >= rmax, and for each primary chunk sweep the neighbor cells
with a static per-cell capacity — every distance evaluation a dense
vectorized op, every histogram a bincount, all inside one jitted
program. Cost is N1 * len(offsets) * K.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ...ops.gridhash import GridHash


def paircount(pos1, w1, pos2, w2, box, edges, mode='1d', Nmu=None,
              pimax=None, los=2, periodic=True, is_auto=False,
              chunk=4096, grid_origin=0.0, pair_los='axis'):
    """Weighted pair counts.

    Parameters
    ----------
    pos1, w1 : primaries (N1, 3), (N1,)
    pos2, w2 : secondaries (may be the same arrays; set is_auto)
    box : (3,) periodic box (used for wrapping when ``periodic``)
    edges : radial bin edges — r for '1d'/'2d', rp for 'projected',
        theta degrees for 'angular'
    mode : '1d' | '2d' | 'projected' | 'angular'
    Nmu : number of mu bins in [0, 1] for mode='2d'
    pimax : max line-of-sight separation, with 1 Mpc/h pi bins, for
        mode='projected'
    los : line-of-sight axis index (0, 1, 2)
    is_auto : self-pairs are excluded; every pair counted twice
        (i<j and j>i), matching the reference's Corrfunc conventions
    grid_origin : (3,) offset subtracted before cell hashing (lets
        non-periodic data sit anywhere)
    pair_los : 'axis' (mu against the ``los`` axis; periodic-box
        convention) or 'midpoint' (mu against the pair midpoint
        direction from the observer at the coordinate origin; the
        Corrfunc-mocks convention for survey data)

    Returns
    -------
    dict with 'npairs' and 'wnpairs' arrays of the binned shape.
    """
    pos1 = np.asarray(pos1, dtype='f8')
    pos2 = np.asarray(pos2, dtype='f8')
    w1 = np.ones(len(pos1)) if w1 is None else np.asarray(w1, 'f8')
    w2 = np.ones(len(pos2)) if w2 is None else np.asarray(w2, 'f8')
    box = np.asarray(box, dtype='f8')
    edges = np.asarray(edges, dtype='f8')

    if mode == 'angular':
        # positions are unit vectors; chord distance bins
        redges = 2 * np.sin(0.5 * np.radians(edges))
        work_box = np.ones(3) * 4.0  # unit sphere fits in [-2,2]
        p1 = pos1 + 2.0
        p2 = pos2 + 2.0
        periodic = False
    else:
        redges = edges
        work_box = box
        p1 = pos1 - grid_origin
        p2 = pos2 - grid_origin

    if mode == '1d':
        rmax = redges[-1]
        nb2 = 1
    elif mode == '2d':
        rmax = redges[-1]
        nb2 = Nmu
    elif mode == 'projected':
        rmax = np.sqrt(redges[-1] ** 2 + pimax ** 2)
        nb2 = int(pimax)
    elif mode == 'angular':
        rmax = redges[-1]
        nb2 = 1
    else:
        raise ValueError("unknown mode %r" % mode)

    nb1 = len(redges) - 1
    grid = GridHash(p2, work_box, rmax, periodic=periodic)
    w2_s = jnp.asarray(w2[grid.order])
    r2edges = jnp.asarray(redges ** 2)
    losj = int(los)
    origin_j = jnp.asarray(np.broadcast_to(
        np.asarray(grid_origin, dtype='f8'), (3,)))
    nbins_flat = (nb1 + 2) * nb2

    def count_chunk(args):
        p1c, w1c, live1 = args  # (C, 3), (C,), (C,)
        ci1 = grid.cell_of(p1c)
        npairs = jnp.zeros(nbins_flat, jnp.float64)
        wpairs = jnp.zeros(nbins_flat, jnp.float64)

        def body(carry, j, valid, dneg, r2):
            npairs, wpairs = carry
            d = -dneg  # primary - secondary, as the bins expect
            # exclude exact self-pairs in autocorrelations
            ok = live1 & valid & ((r2 > 0) if is_auto else (r2 >= 0))
            dig_r = jnp.digitize(r2, r2edges)

            if pair_los == 'midpoint' and mode in ('2d', 'projected'):
                # observer at the (pre-shift) coordinate origin
                mid = 0.5 * (p1c + grid.pos_s[j]) + origin_j
                mnorm = jnp.sqrt(jnp.sum(mid * mid, axis=-1))
                dlos = jnp.abs(jnp.sum(d * mid, axis=-1)) \
                    / jnp.where(mnorm == 0, 1.0, mnorm)
            else:
                dlos = jnp.abs(d[:, losj])

            if mode == '2d':
                rr = jnp.sqrt(jnp.where(r2 == 0, 1.0, r2))
                mu = jnp.where(r2 == 0, 0.0, dlos / rr)
                dig_2 = jnp.clip((mu * nb2).astype(jnp.int32), 0,
                                 nb2 - 1)
            elif mode == 'projected':
                drp2 = r2 - dlos * dlos
                dig_r = jnp.digitize(drp2, r2edges)
                dig_2 = jnp.clip(dlos.astype(jnp.int32), 0, nb2 - 1)
                ok = ok & (dlos < pimax)
            else:
                dig_2 = 0

            idx = dig_r * nb2 + dig_2
            # the overflow radial bin absorbs masked-out slots
            idx = jnp.where(ok, idx, (nb1 + 1) * nb2)
            npairs = npairs + jnp.bincount(
                idx, weights=jnp.where(ok, 1.0, 0.0),
                length=nbins_flat)
            wpairs = wpairs + jnp.bincount(
                idx, weights=jnp.where(ok, w1c * w2_s[j], 0.0),
                length=nbins_flat)
            return npairs, wpairs

        return grid.fold(p1c, ci1, body, (npairs, wpairs))

    N1 = len(p1)
    nchunks = max(1, (N1 + chunk - 1) // chunk)
    npad = nchunks * chunk
    p1p = np.concatenate([p1, np.zeros((npad - N1, 3))])
    w1p = np.concatenate([w1, np.zeros(npad - N1)])
    live = np.concatenate([np.ones(N1, bool), np.zeros(npad - N1, bool)])
    p1j = jnp.asarray(p1p).reshape(nchunks, chunk, 3)
    w1j = jnp.asarray(w1p).reshape(nchunks, chunk)
    livej = jnp.asarray(live).reshape(nchunks, chunk)

    counts = jax.lax.map(count_chunk, (p1j, w1j, livej))
    npairs = np.array(counts[0].sum(axis=0)).reshape(nb1 + 2, nb2)
    wpairs = np.array(counts[1].sum(axis=0)).reshape(nb1 + 2, nb2)

    # keep only in-range radial bins (1..nb1)
    npairs = npairs[1:nb1 + 1]
    wpairs = wpairs[1:nb1 + 1]
    return dict(npairs=npairs.squeeze(), wnpairs=wpairs.squeeze())
