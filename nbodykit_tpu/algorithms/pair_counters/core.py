"""The TPU pair-counting kernel.

Replaces the Corrfunc C/AVX kernels the reference wraps
(nbodykit/algorithms/pair_counters/corrfunc/*; SURVEY.md §2.3): weighted
pair counts binned in r, (r, mu), (rp, pi), or theta.

Two drivers share one counting body:

- :func:`paircount` — single-device: host :class:`...ops.gridhash.GridHash`
  prep + chunked ``lax.map`` sweep;
- :func:`paircount_dist` — device-mesh: primaries routed tight to x-slab
  owners, secondaries routed with both-side ghost copies within rmax
  (:func:`...parallel.domain.slab_route` — the analog of the
  reference's ``decompose_box_data``/``decompose_survey_data``,
  nbodykit/algorithms/pair_counters/domain.py:47-283), then a fully
  in-graph :class:`...ops.devicehash.DeviceGridHash` sweep per device
  inside ``shard_map``, histograms ``psum``-reduced. No device ever
  holds the full particle set.

Every distance evaluation is a dense vectorized op, every histogram a
bincount, all inside one jitted program.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ...ops.gridhash import GridHash
from ...utils import working_dtype
from ...ops.devicehash import DeviceGridHash

# one-time latch for the f8->f4 demotion diagnostic below: the event
# is per-process (the contract does not change mid-run), so the
# counter/trace noise must not scale with the chunk count
_demotion_noted = [False]


def _accumulator_dtype():
    """The pair-histogram accumulator dtype: f8 when x64 is enabled,
    else f4 — and when that demotion happens it is OBSERVABLE, not
    silent: the first call bumps the one-time ``precision.demoted``
    counter and emits a trace event naming the site.  Accumulating
    ~N*s^3 weighted counts in f4 loses ~eps*sqrt(n_pairs) relative
    mass per bin; callers needing the f8 contract must enable x64
    (``jax.config.update('jax_enable_x64', True)``)."""
    wdt = working_dtype('f8')
    if wdt.itemsize < 8 and not _demotion_noted[0]:
        _demotion_noted[0] = True
        from ...diagnostics import counter, current_tracer
        counter('precision.demoted').add(1)
        tr = current_tracer()
        if tr is not None:
            tr.event('precision.demoted',
                     {'site': 'pair_counters.core',
                      'requested': 'f8', 'effective': wdt.name})
    return wdt


def rmax_of(mode, edges, pimax=None):
    """Max interaction radius of a mode/edges combination (used by
    callers to decide whether the slab-decomposed driver fits)."""
    edges = np.asarray(edges, dtype='f8')
    if mode == 'angular':
        return float(2 * np.sin(0.5 * np.radians(edges[-1])))
    if mode == 'projected':
        return float(np.sqrt(edges[-1] ** 2 + pimax ** 2))
    return float(edges[-1])


def _mode_setup(pos1, pos2, box, edges, mode, Nmu, pimax, grid_origin,
                periodic):
    """Shared mode normalization: work coordinates (>= 0), working box,
    squared radial edges, bin counts, max interaction radius."""
    box = np.asarray(box, dtype='f8')
    edges = np.asarray(edges, dtype='f8')
    if mode == 'angular':
        # positions are unit vectors; chord distance bins
        redges = 2 * np.sin(0.5 * np.radians(edges))
        work_box = np.ones(3) * 4.0  # unit sphere fits in [-2,2]
        p1 = pos1 + 2.0
        p2 = pos2 + 2.0
        periodic = False
    else:
        redges = edges
        work_box = box
        p1 = pos1 - grid_origin
        p2 = pos2 - grid_origin

    if mode == '1d':
        rmax, nb2 = redges[-1], 1
    elif mode == '2d':
        rmax, nb2 = redges[-1], Nmu
    elif mode == 'projected':
        rmax, nb2 = np.sqrt(redges[-1] ** 2 + pimax ** 2), int(pimax)
    elif mode == 'angular':
        rmax, nb2 = redges[-1], 1
    else:
        raise ValueError("unknown mode %r" % mode)
    nb1 = len(redges) - 1
    return p1, p2, work_box, redges, float(rmax), nb1, nb2, periodic


def _fold_body(grid, w2_s, r2edges, mode, nb1, nb2, pimax, losj,
               origin_j, pair_los, is_auto, p1c, w1c, live1):
    """The per-candidate accumulation body shared by both drivers.

    ``grid`` is a GridHash or DeviceGridHash; ``w2_s`` its sorted
    secondary weights. Returns a body for ``grid.fold`` accumulating
    (npairs, wpairs) flat histograms of length (nb1+2)*nb2.
    """
    nbins_flat = (nb1 + 2) * nb2

    def body(carry, j, valid, dneg, r2):
        npairs, wpairs = carry
        d = -dneg  # primary - secondary, as the bins expect
        # exclude exact self-pairs in autocorrelations
        ok = live1 & valid & ((r2 > 0) if is_auto else (r2 >= 0))
        dig_r = jnp.digitize(r2, r2edges)

        if pair_los == 'midpoint' and mode in ('2d', 'projected'):
            # observer at the (pre-shift) coordinate origin
            mid = 0.5 * (p1c + grid.pos_s[j]) + origin_j
            mnorm = jnp.sqrt(jnp.sum(mid * mid, axis=-1))
            dlos = jnp.abs(jnp.sum(d * mid, axis=-1)) \
                / jnp.where(mnorm == 0, 1.0, mnorm)
        else:
            dlos = jnp.abs(d[:, losj])

        if mode == '2d':
            rr = jnp.sqrt(jnp.where(r2 == 0, 1.0, r2))
            mu = jnp.where(r2 == 0, 0.0, dlos / rr)
            dig_2 = jnp.clip((mu * nb2).astype(jnp.int32), 0, nb2 - 1)
        elif mode == 'projected':
            drp2 = r2 - dlos * dlos
            dig_r = jnp.digitize(drp2, r2edges)
            dig_2 = jnp.clip(dlos.astype(jnp.int32), 0, nb2 - 1)
            ok = ok & (dlos < pimax)
        else:
            dig_2 = 0

        idx = dig_r * nb2 + dig_2
        # the overflow radial bin absorbs masked-out slots
        idx = jnp.where(ok, idx, (nb1 + 1) * nb2)
        npairs = npairs + jnp.bincount(
            idx, weights=jnp.where(ok, 1.0, 0.0), length=nbins_flat)
        wpairs = wpairs + jnp.bincount(
            idx, weights=jnp.where(ok, w1c * w2_s[j], 0.0),
            length=nbins_flat)
        return npairs, wpairs

    return body


def _package(npairs, wpairs, nb1, nb2):
    npairs = np.array(npairs).reshape(nb1 + 2, nb2)
    wpairs = np.array(wpairs).reshape(nb1 + 2, nb2)
    # keep only in-range radial bins (1..nb1)
    return dict(npairs=npairs[1:nb1 + 1].squeeze(),
                wnpairs=wpairs[1:nb1 + 1].squeeze())


def paircount(pos1, w1, pos2, w2, box, edges, mode='1d', Nmu=None,
              pimax=None, los=2, periodic=True, is_auto=False,
              chunk=4096, grid_origin=0.0, pair_los='axis'):
    """Weighted pair counts (single-device driver).

    Parameters
    ----------
    pos1, w1 : primaries (N1, 3), (N1,)
    pos2, w2 : secondaries (may be the same arrays; set is_auto)
    box : (3,) periodic box (used for wrapping when ``periodic``)
    edges : radial bin edges — r for '1d'/'2d', rp for 'projected',
        theta degrees for 'angular'
    mode : '1d' | '2d' | 'projected' | 'angular'
    Nmu : number of mu bins in [0, 1] for mode='2d'
    pimax : max line-of-sight separation, with 1 Mpc/h pi bins, for
        mode='projected'
    los : line-of-sight axis index (0, 1, 2)
    is_auto : self-pairs are excluded; every pair counted twice
        (i<j and j>i), matching the reference's Corrfunc conventions
    grid_origin : (3,) offset subtracted before cell hashing (lets
        non-periodic data sit anywhere)
    pair_los : 'axis' (mu against the ``los`` axis; periodic-box
        convention) or 'midpoint' (mu against the pair midpoint
        direction from the observer at the coordinate origin; the
        Corrfunc-mocks convention for survey data)

    Returns
    -------
    dict with 'npairs' and 'wnpairs' arrays of the binned shape.

    Notes
    -----
    Histograms accumulate at :func:`_accumulator_dtype`: f8 under
    x64, else f4 — the demotion bumps the one-time
    ``precision.demoted`` counter/trace event rather than happening
    silently.
    """
    pos1 = np.asarray(pos1, dtype='f8')
    pos2 = np.asarray(pos2, dtype='f8')
    w1 = np.ones(len(pos1)) if w1 is None else np.asarray(w1, 'f8')
    w2 = np.ones(len(pos2)) if w2 is None else np.asarray(w2, 'f8')
    wdt = _accumulator_dtype()  # f4 when x64 is off — observable

    p1, p2, work_box, redges, rmax, nb1, nb2, periodic = _mode_setup(
        pos1, pos2, box, edges, mode, Nmu, pimax, grid_origin, periodic)

    grid = GridHash(p2, work_box, rmax, periodic=periodic)
    w2_s = jnp.asarray(w2[grid.order])
    r2edges = jnp.asarray(redges ** 2)
    losj = int(los)
    origin_j = jnp.asarray(np.broadcast_to(
        np.asarray(grid_origin, dtype='f8'), (3,)))
    nbins_flat = (nb1 + 2) * nb2

    def count_chunk(args):
        p1c, w1c, live1 = args  # (C, 3), (C,), (C,)
        ci1 = grid.cell_of(p1c)
        body = _fold_body(grid, w2_s, r2edges, mode, nb1, nb2, pimax,
                          losj, origin_j, pair_los, is_auto,
                          p1c, w1c, live1)
        init = (jnp.zeros(nbins_flat, wdt),
                jnp.zeros(nbins_flat, wdt))
        return grid.fold(p1c, ci1, body, init)

    N1 = len(p1)
    nchunks = max(1, (N1 + chunk - 1) // chunk)
    npad = nchunks * chunk
    p1p = np.concatenate([p1, np.zeros((npad - N1, 3))])
    w1p = np.concatenate([w1, np.zeros(npad - N1)])
    live = np.concatenate([np.ones(N1, bool), np.zeros(npad - N1, bool)])
    p1j = jnp.asarray(p1p).reshape(nchunks, chunk, 3)
    w1j = jnp.asarray(w1p).reshape(nchunks, chunk)
    livej = jnp.asarray(live).reshape(nchunks, chunk)

    counts = jax.lax.map(count_chunk, (p1j, w1j, livej))
    return _package(counts[0].sum(axis=0), counts[1].sum(axis=0),
                    nb1, nb2)


def paircount_dist(pos1, w1, pos2, w2, box, edges, mesh, mode='1d',
                   Nmu=None, pimax=None, los=2, periodic=True,
                   is_auto=False, grid_origin=0.0, pair_los='axis',
                   max_ncell=4096):
    """Weighted pair counts over the device mesh.

    Same contract as :func:`paircount`, but pos/w arrive as global
    sharded jnp arrays and the counting runs domain-decomposed: no
    device ever gathers the catalogs. Requires rmax <= work_box_x / P
    (single-hop ghosts); callers fall back to :func:`paircount` when
    that fails.  Coordinates and histograms use
    :func:`_accumulator_dtype` (f8 under x64, else f4 — the demotion
    is counted, not silent).
    """
    from jax.sharding import PartitionSpec as P
    from ...parallel.domain import slab_route
    from ...parallel.runtime import AXIS, shard_leading

    wdt = _accumulator_dtype()  # f4 when x64 is off — observable
    pos1 = jnp.asarray(pos1, wdt)
    pos2 = jnp.asarray(pos2, wdt)
    n1 = pos1.shape[0]
    n2 = pos2.shape[0]
    w1 = jnp.ones(n1, wdt) if w1 is None \
        else jnp.asarray(w1, wdt)
    w2 = jnp.ones(n2, wdt) if w2 is None \
        else jnp.asarray(w2, wdt)

    p1, p2, work_box, redges, rmax, nb1, nb2, periodic = _mode_setup(
        pos1, pos2, box, edges, mode, Nmu, pimax, grid_origin, periodic)

    # route primaries tight, secondaries with ghosts on both faces;
    # slab boundaries are balanced on the primaries' histogram
    # (reference pair_counters/domain.py:256) and SHARED by both
    # routes so every primary sees its rmax-neighborhood
    route1, f1, live1 = slab_route(p1, work_box, rmax, mesh,
                                   ghosts=None, periodic=periodic,
                                   balance=True)
    route2, f2, live2 = slab_route(p2, work_box, rmax, mesh,
                                   ghosts='both', periodic=periodic,
                                   edges=route1.edges)
    (p1_r, w1_r), ok1, _ = route1.exchange([p1, w1])
    (p2_r, w2_r, lv2), ok2, _ = route2.exchange(
        [jnp.concatenate([p2] * f2), jnp.concatenate([w2] * f2), live2])
    ok2 = ok2 & lv2

    r2edges = jnp.asarray(redges ** 2)
    losj = int(los)
    origin_j = jnp.asarray(np.broadcast_to(
        np.asarray(grid_origin, dtype='f8'), (3,)))
    nbins_flat = (nb1 + 2) * nb2

    def local(p1_l, w1_l, ok1_l, p2_l, w2_l, ok2_l):
        grid = DeviceGridHash(p2_l, work_box, rmax, valid=ok2_l,
                              periodic=periodic, max_ncell=max_ncell,
                              axis_name=AXIS)
        w2_s = w2_l[grid.order]
        ci1 = grid.cell_of(p1_l)
        body = _fold_body(grid, w2_s, r2edges, mode, nb1, nb2, pimax,
                          losj, origin_j, pair_los, is_auto,
                          p1_l, w1_l, ok1_l)
        init = (jnp.zeros(nbins_flat, wdt),
                jnp.zeros(nbins_flat, wdt))
        npairs, wpairs = grid.fold(p1_l, ci1, body, init)
        return (jax.lax.psum(npairs, AXIS),
                jax.lax.psum(wpairs, AXIS))

    npairs, wpairs = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS), P(AXIS),
                  P(AXIS, None), P(AXIS), P(AXIS)),
        out_specs=(P(), P())))(p1_r, w1_r, ok1, p2_r, w2_r, ok2)
    return _package(npairs, wpairs, nb1, nb2)
