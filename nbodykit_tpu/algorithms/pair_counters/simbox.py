"""SimulationBoxPairCount: pair counts in a periodic box.

Reference: ``nbodykit/algorithms/pair_counters/simbox.py:6`` (wrapping
Corrfunc theory kernels DD/DDsmu/DDrppi). Here the grid-hash kernel of
:mod:`.core` does the counting on device.
"""

import numpy as np

from .base import PairCountBase, package_result
from .core import paircount, paircount_dist, rmax_of
from ...parallel.runtime import mesh_size
from ...utils import as_numpy


class SimulationBoxPairCount(PairCountBase):
    """Count weighted pairs in bins of separation.

    Parameters (reference simbox.py): mode in
    {'1d','2d','projected','angular'}, first/second catalogs, edges,
    BoxSize, periodic, weight column, Nmu, pimax, los ('x'|'y'|'z').

    Results in :attr:`pairs` (npairs, wnpairs); attrs hold the total
    weighted pair normalizations used by the estimators.
    """

    def __init__(self, mode, first, edges, BoxSize=None, periodic=True,
                 weight='Weight', second=None, los='z', Nmu=None,
                 pimax=None, show_progress=False):
        if mode not in ('1d', '2d', 'projected', 'angular'):
            raise ValueError("invalid mode %r" % mode)
        if mode == '2d' and Nmu is None:
            raise ValueError("mode='2d' requires Nmu")
        if mode == 'projected' and pimax is None:
            raise ValueError("mode='projected' requires pimax")
        los_i = {'x': 0, 'y': 1, 'z': 2}[los]

        if BoxSize is None:
            BoxSize = first.attrs['BoxSize']
        BoxSize = np.ones(3) * np.asarray(BoxSize, dtype='f8')

        self.first = first
        self.second = second
        self.comm = first.comm
        self.attrs = dict(mode=mode, edges=np.asarray(edges),
                          BoxSize=BoxSize, periodic=periodic, los=los,
                          Nmu=Nmu, pimax=pimax, weight=weight)

        # device-mesh path: catalogs stay sharded, counting is domain-
        # decomposed (reference decompose_box_data, pair_counters/
        # domain.py:47-132); fall back to the single-device driver when
        # rmax exceeds the slab width or there is one device
        nproc = mesh_size(self.comm)
        rmax = rmax_of(mode, edges, pimax)
        workx = 4.0 if mode == 'angular' else BoxSize[0]
        use_dist = nproc > 1 and rmax <= workx / nproc

        def get(cat, col, conv):
            if col not in cat:
                return None
            return conv(cat[col])

        conv = (lambda x: x) if use_dist else as_numpy
        import jax.numpy as jnp
        aspos = (lambda x: jnp.asarray(x)) if use_dist else as_numpy

        pos1 = aspos(first['Position'])
        w1 = get(first, weight, conv)
        if second is None or second is first:
            pos2, w2 = pos1, w1
            is_auto = True
        else:
            pos2 = aspos(second['Position'])
            w2 = get(second, weight, conv)
            is_auto = False

        kw = dict(mode=mode, Nmu=Nmu, pimax=pimax, los=los_i,
                  periodic=periodic, is_auto=is_auto)
        if use_dist:
            counts = paircount_dist(pos1, w1, pos2, w2, BoxSize, edges,
                                    self.comm, **kw)
        else:
            counts = paircount(pos1, w1, pos2, w2, BoxSize, edges, **kw)

        W1 = float(np.sum(w1)) if w1 is not None else float(len(pos1))
        W2 = float(np.sum(w2)) if w2 is not None else float(len(pos2))
        if is_auto:
            sumw2 = float(np.sum(np.asarray(w1) ** 2)) \
                if w1 is not None else float(len(pos1))
            total = W1 * W1 - sumw2
        else:
            total = W1 * W2
        self.attrs['total_wnpairs'] = total
        self.attrs['W1'] = W1
        self.attrs['W2'] = W2
        self.attrs['N1'] = len(pos1)
        self.attrs['N2'] = len(pos2)
        self.attrs['is_auto'] = is_auto

        self.pairs = package_result(counts, **self.attrs)
