"""FFT-based power spectrum estimators for periodic boxes.

Reference: ``nbodykit/algorithms/fftpower.py`` (FFTBase :12, FFTPower
:146, ProjectedFFTPower :361, project_to_basis :507). Capability parity:

- P(k) / P(k,mu) / multipoles P_ell(k) with the same binning semantics
  (under/overflow bins, half-open mu bins with an inclusive last bin,
  hermitian double-count weights, Nyquist planes counted once);
- dk=0 "unique edges" mode; save/load via JSON.

TPU redesign: the 3-D power and its (k, mu, ell) reduction run as one
jitted XLA program over the sharded transposed complex field — digitize
+ Legendre recurrence + weighted bincounts replace the reference's
rank-local slab loop (HOT LOOP 2 of SURVEY.md §3.1); means/packaging
happen on host with numpy (small arrays).
"""

import json
import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..base.catalog import CatalogSourceBase
from ..base.mesh import MeshSource, Field, FieldMesh
from ..binned_statistic import BinnedStatistic
from ..diagnostics import NULL_SPAN, instrumented_jit, span_eager
from ..utils import JSONEncoder, JSONDecoder, as_numpy, working_dtype


def _legendre_all(ells, mu):
    """Evaluate Legendre P_ell(mu) for each ell in ``ells`` via the
    recurrence (jit-friendly; no scipy)."""
    lmax = max(ells) if ells else 0
    P_prev = jnp.ones_like(mu)           # P_0
    out = {0: P_prev}
    if lmax >= 1:
        P_cur = mu                       # P_1
        out[1] = P_cur
        for n in range(1, lmax):
            P_next = ((2 * n + 1) * mu * P_cur - n * P_prev) / (n + 1)
            P_prev, P_cur = P_cur, P_next
            out[n + 1] = P_cur
    return [out[ell] for ell in ells]


# elements per slab chunk of the binning reduction (patchable so tests
# can exercise the chunked path on small meshes)
_BIN_CHUNK_ELEMENTS = 1 << 22


def project_to_basis(y3d, edges, los=[0, 0, 1], poles=[]):
    """Bin a 3-D statistic into (x, mu) bins and optional multipoles.

    Parameters
    ----------
    y3d : Field — either a transposed hermitian-compressed complex field
        (binned in k) or a real field (binned in separation r, fftfreq
        ordering)
    edges : [xedges, muedges]
    los : unit line-of-sight vector
    poles : list of int multipoles

    Returns
    -------
    (xmean_2d, mumean_2d, y2d, N_2d), (xmean_1d, poles, N_1d) or None

    Semantics mirror the reference's project_to_basis
    (algorithms/fftpower.py:507-701): digitize against squared x edges,
    hermitian weights double-count kz>0 (excluding the Nyquist plane),
    odd multipoles keep 2i*Im, even keep 2*Re on the doubled modes.
    """
    pm = y3d.pm
    # a complex field with the full (uncompressed) kz axis is a c2c
    # spectrum: all modes present, no hermitian double-counting
    full_complex = (y3d.kind == 'complex'
                    and y3d.shape[2] == int(pm.Nmesh[2]))
    hermitian = (y3d.kind == 'complex') and not full_complex
    xedges, muedges = edges
    Nx = len(xedges) - 1
    Nmu = len(muedges) - 1

    do_poles = len(poles) > 0
    _poles = sorted(set([0]) | set(poles))
    Nell = len(_poles)
    ell_idx = [_poles.index(l) for l in poles]
    if any(ell < 0 for ell in _poles):
        raise ValueError("multipole numbers must be non-negative integers")

    nbins = (Nx + 2) * (Nmu + 2)

    N0, N1, N2 = pm.shape_real
    L = pm.BoxSize
    # best available precision for the mode coordinates/weights: f8
    # under x64, f4 on TPU — an explicit demotion decision (NBK301)
    # instead of a silent one (jnp.float64 with x64 off quietly
    # returns f32)
    _f8 = working_dtype('f8')
    if hermitian or full_complex:
        kx, ky, kz = pm.k_list(dtype=_f8, full=full_complex)
        coords = [kx * los[0], ky * los[1], kz * los[2]]
        x2fac = [kx ** 2, ky ** 2, kz ** 2]
        units = 2 * np.pi / np.asarray(L, dtype='f8')
        if full_complex:
            w_b = jnp.ones((1, 1, 1), dtype=_f8)
        else:
            w_b = pm.hermitian_weights(dtype=_f8)  # (1,1,nz)
    else:
        # real field: separation coordinates in fftfreq ordering
        rx = (jnp.fft.fftfreq(N0, d=1.0 / N0) * (L[0] / N0)
              ).reshape(N0, 1, 1)
        ry = (jnp.fft.fftfreq(N1, d=1.0 / N1) * (L[1] / N1)
              ).reshape(1, N1, 1)
        rz = (jnp.fft.fftfreq(N2, d=1.0 / N2) * (L[2] / N2)
              ).reshape(1, 1, N2)
        coords = [rx * los[0], ry * los[1], rz * los[2]]
        x2fac = [rx ** 2, ry ** 2, rz ** 2]
        units = np.asarray(L, dtype='f8') / np.asarray(
            [N0, N1, N2], dtype='f8')
        w_b = jnp.ones((1, 1, 1), dtype=_f8)

    # Exact-integer lattice binning for the no-x64 (TPU) regime. With
    # f64 unavailable, x^2 computed in f32 rounds differently from the
    # f64 reference and modes sitting exactly ON a bin edge (any
    # perfect-square |i|^2 when dk is the fundamental) flip bins
    # unpredictably. On a uniform lattice x^2 = unit^2 * |i|^2 with
    # |i|^2 an exact int32, so digitizing |i|^2 (exactly representable
    # in f32 up to Nmesh=4096) against host-f64-quantized edges
    # (xedges/unit)^2 is deterministic and edge-exact — the f32 story
    # of round-2 VERDICT weak #3. The x64 path is left byte-identical.
    # the |i|^2 lattice must stay exactly representable in f32
    # (< 2^24), i.e. Nmesh <= 4096 — beyond that the cast itself
    # rounds and the path would reintroduce the edge flips it fixes
    _isq_max = 3 * (max(N0, N1, N2) // 2) ** 2
    exact_int = (not jax.config.jax_enable_x64) \
        and np.allclose(units, units[0], rtol=1e-12) \
        and _isq_max < (1 << 24)
    if exact_int:
        unit = float(units[0])
        if hermitian or full_complex:
            ix, iy, iz = pm.i_list_complex()
            if full_complex:
                iz = jnp.fft.fftfreq(N2, d=1.0 / N2).astype(
                    jnp.int32).reshape(1, 1, N2)
        else:
            ix = jnp.fft.fftfreq(N0, d=1.0 / N0).astype(
                jnp.int32).reshape(N0, 1, 1)
            iy = jnp.fft.fftfreq(N1, d=1.0 / N1).astype(
                jnp.int32).reshape(1, N1, 1)
            iz = jnp.fft.fftfreq(N2, d=1.0 / N2).astype(
                jnp.int32).reshape(1, 1, N2)
        x2fac = [ix * ix, iy * iy, iz * iz]  # int32, exact
        # integer edge thresholds: for integer v, (e <= v) == (ceil(e)
        # <= v), so digitizing int32 |i|^2 against the ceil'd edges is
        # FULLY exact — see ops.histogram.lattice_shell_edges
        from ..ops.histogram import lattice_shell_edges
        x2edges = jnp.asarray(lattice_shell_edges(xedges, unit))
    else:
        unit = 1.0
        x2edges = jnp.asarray(np.asarray(xedges, dtype='f8') ** 2)
    muedges_j = jnp.asarray(np.asarray(muedges, dtype='f8'))

    value = y3d.value
    is_cplx = jnp.iscomplexobj(value)

    # slab-chunk the reduction over the leading axis so no full-mesh
    # f64 temporary (x2 / mu / legendre / digitize) is ever live at
    # once — at Nmesh >= 1024 the unchunked version needs several
    # multi-GB buffers (round-1 VERDICT weak #6). With a device mesh
    # the same chunking runs per-device inside shard_map (each device
    # loops over its own rows and psums the small histograms) — the
    # per-device memory hazard is worst exactly in the multi-chip
    # configuration (round-2 VERDICT weak #4).
    from ..parallel.runtime import mesh_size, AXIS
    S0, S1, S2 = (int(s) for s in value.shape)
    try:
        nproc = mesh_size(getattr(pm, 'comm', None))
    except Exception:
        nproc = 1
    if nproc > 1 and S0 % nproc != 0:
        nproc = 1  # unexpected layout: fused single-program path
    S0_local = S0 // nproc
    target_rows = max(1, _BIN_CHUNK_ELEMENTS // max(1, S1 * S2))
    rows = min(S0_local, target_rows)
    while S0_local % rows:
        rows -= 1
    nch = S0_local // rows
    chunked = nch > 1
    if not chunked:
        rows = S0_local

    def slice0(a, start):
        """Slice the leading axis of a broadcastable factor at a global
        row offset. Whether a factor varies along axis 0 depends on the
        layout (transposed complex: ky leads; real: rx leads) — size-1
        axes pass through."""
        if a.shape[0] == 1:
            return a
        return jax.lax.dynamic_slice_in_dim(a, start, rows, 0)

    from ..ops.histogram import hist2d_weighted

    def chunk_hists(v_c, start):
        """All weighted histograms of one leading-axis slab whose
        global row offset is ``start``."""
        x2 = sum(slice0(f, start) for f in x2fac)
        if exact_int:
            # x2 stays int32 for the (exact) digitize; float only for
            # the mean-|x| stream
            xnorm = unit * jnp.sqrt(x2.astype(jnp.float32))
        else:
            xnorm = jnp.sqrt(x2)
        mudot = sum(slice0(c, start) for c in coords)
        mu = jnp.where(xnorm == 0, 0.0,
                       mudot / jnp.where(xnorm == 0, 1.0, xnorm))
        shape = v_c.shape
        dig_x = jnp.digitize(
            jnp.broadcast_to(x2, shape).reshape(-1), x2edges)
        dig_mu = jnp.digitize(
            jnp.broadcast_to(mu, shape).reshape(-1), muedges_j)

        wf = jnp.broadcast_to(w_b, shape).reshape(-1)
        nonsing = (wf == 2.0)
        xw = jnp.broadcast_to(xnorm, shape).reshape(-1) * wf
        muw = jnp.broadcast_to(mu, shape).reshape(-1) * wf

        streams = [xw, muw, wf]
        legs = _legendre_all(_poles, mu)
        # accumulate the spectrum in the widest dtype the backend has
        # (f8 under x64, f4 on TPU) — explicit, not silently demoted
        vre = v_c.real.astype(working_dtype('f8')).reshape(-1)
        vim = (v_c.imag.astype(working_dtype('f8')).reshape(-1)
               if is_cplx else None)
        for iell, ell in enumerate(_poles):
            leg = jnp.broadcast_to(legs[iell], shape).reshape(-1)
            yre = leg * vre
            yim = leg * vim if is_cplx else None
            if hermitian:
                if ell % 2:   # odd: real parts cancel between +k/-k
                    yre = jnp.where(nonsing, 0.0, yre)
                    yim = jnp.where(nonsing, 2.0 * yim, yim)
                else:         # even: imaginary parts cancel
                    yre = jnp.where(nonsing, 2.0 * yre, yre)
                    if is_cplx:
                        yim = jnp.where(nonsing, 0.0, yim)
            fac = (2.0 * ell + 1.0)
            streams.append(fac * yre)
            if is_cplx:
                streams.append(fac * yim)
        return hist2d_weighted(dig_x, dig_mu, streams,
                               Nx + 2, Nmu + 2)

    nstreams = 3 + Nell * (2 if is_cplx else 1)

    def _block_hists(v_loc, base, varying=False):
        """Histograms of one device's (S0_local, S1, S2) block starting
        at global row ``base``, chunk-looped so only ``rows`` rows of
        temporaries are live. Cross-chunk sums are Kahan-compensated:
        in the no-x64 (TPU) regime the carry is f32 and a plain sum
        over many chunks loses low bits of the per-bin totals."""
        if not chunked:
            return list(chunk_hists(v_loc, base))

        def body(i, state):
            acc, comp = state
            hs_c = chunk_hists(
                jax.lax.dynamic_slice_in_dim(v_loc, i * rows, rows, 0),
                base + i * rows)
            new_acc, new_comp = [], []
            for a, c, h in zip(acc, comp, hs_c):
                y = h - c
                t = a + y
                new_comp.append((t - a) - y)
                new_acc.append(t)
            return (new_acc, new_comp)
        init_a = [jnp.zeros((Nx + 2, Nmu + 2), hist_dtype)
                  for _ in range(nstreams)]
        init_c = [jnp.zeros((Nx + 2, Nmu + 2), hist_dtype)
                  for _ in range(nstreams)]
        if varying:
            # inside shard_map the body outputs are device-varying;
            # the carry init must carry the same vma type
            def _vary(a):
                pcast = getattr(jax.lax, 'pcast', None)
                if pcast is not None:
                    return pcast(a, AXIS, to='varying')
                return jax.lax.pvary(a, AXIS)
            init_a = [_vary(a) for a in init_a]
            init_c = [_vary(a) for a in init_c]
        acc, _ = jax.lax.fori_loop(0, nch, body, (init_a, init_c))
        return acc

    hist_dtype = jnp.float64 if jax.config.jax_enable_x64 \
        else jnp.float32

    if nproc > 1:
        from jax.sharding import PartitionSpec as _P

        def _local(v_loc):
            base = jax.lax.axis_index(AXIS) * S0_local
            hs = _block_hists(v_loc, base, varying=True)
            return tuple(jax.lax.psum(h, AXIS) for h in hs)

        _bin = instrumented_jit(jax.shard_map(
            _local, mesh=pm.comm,
            in_specs=(_P(AXIS, None, None),),
            out_specs=(_P(),) * nstreams), label='fftpower.binning')
    else:
        _bin = instrumented_jit(lambda v: tuple(_block_hists(v, 0)),
                                label='fftpower.binning')

    _sp = span_eager('fftpower.binning', nstreams=nstreams,
                     shape=[int(s) for s in value.shape])
    with _sp:
        hs = _bin(value)
        if _sp is not NULL_SPAN:
            # binning is async-dispatched; sync inside the span so its
            # wall is the work, not the dispatch (enabled-mode only)
            hs = jax.block_until_ready(hs)
    xsum, musum, Nsum = hs[0], hs[1], hs[2]
    ys_re, ys_im = [], []
    k = 3
    for _ in _poles:
        ys_re.append(np.asarray(hs[k])); k += 1
        if is_cplx:
            ys_im.append(np.asarray(hs[k])); k += 1
        else:
            ys_im.append(np.zeros_like(np.asarray(hs[0])))
    ys_re = np.stack([y.reshape(-1) for y in ys_re])
    ys_im = np.stack([y.reshape(-1) for y in ys_im])

    # host-side: small (Nell, Nx+2, Nmu+2) arrays (np.array: writable copy)
    xsum = np.array(xsum, dtype='f8').reshape(Nx + 2, Nmu + 2)
    musum = np.array(musum, dtype='f8').reshape(Nx + 2, Nmu + 2)
    Nsum = np.array(Nsum, dtype='f8').reshape(Nx + 2, Nmu + 2)
    ysum = (np.asarray(ys_re, dtype='f8')
            + 1j * np.asarray(ys_im, dtype='f8')
            ).reshape(Nell, Nx + 2, Nmu + 2)
    if not jnp.iscomplexobj(value):
        ysum = ysum.real

    # fold the internal mu == 1 bin into the last visible bin
    xsum[:, -2] += xsum[:, -1]
    musum[:, -2] += musum[:, -1]
    Nsum[:, -2] += Nsum[:, -1]
    ysum[..., -2] += ysum[..., -1]

    sl = slice(1, -1)
    with np.errstate(invalid='ignore', divide='ignore'):
        y2d = (ysum[0] / Nsum)[sl, sl]
        xmean_2d = (xsum / Nsum)[sl, sl]
        mumean_2d = (musum / Nsum)[sl, sl]
        N_2d = Nsum[sl, sl]

        pole_result = None
        if do_poles:
            N_1d = Nsum[sl, sl].sum(axis=-1)
            xmean_1d = xsum[sl, sl].sum(axis=-1) / N_1d
            pole_arr = ysum[:, sl, sl].sum(axis=-1) / N_1d
            pole_arr = pole_arr[ell_idx, ...]
            pole_result = (xmean_1d, pole_arr, N_1d)

    return (xmean_2d, mumean_2d, y2d, N_2d), pole_result


def _cast_source(source, BoxSize, Nmesh):
    """Coerce input to a MeshSource (reference fftpower.py:703-730)."""
    if isinstance(source, Field):
        source = FieldMesh(source)
    elif isinstance(source, CatalogSourceBase) and \
            not isinstance(source, MeshSource):
        # honor set_options(mesh_dtype=...): 'f4' (the default) keeps
        # the reference's 'f8' request — working_dtype canonicalizes it
        # to f4 where x64 is off (TPU) — while 'bf16' halves the mesh
        # storage (compute stays f32; see pmesh.ParticleMesh)
        from .. import _global_options
        mdt = _global_options['mesh_dtype']
        if mdt == 'auto':
            from ..tune.resolve import resolve_mesh_dtype
            mdt = resolve_mesh_dtype(
                nmesh=None if Nmesh is None
                else int(np.max(np.atleast_1d(Nmesh))))
        dtype = 'f8' if mdt in (None, 'f4') else mdt
        source = source.to_mesh(BoxSize=BoxSize, Nmesh=Nmesh,
                                dtype=dtype, compensated=True)
    if not isinstance(source, MeshSource):
        raise TypeError("unknown source type for FFT algorithm: %s"
                        % type(source))
    if BoxSize is not None and np.any(
            source.attrs['BoxSize'] != np.atleast_1d(BoxSize)):
        raise ValueError("mismatched BoxSize between argument and source")
    if Nmesh is not None and np.any(
            source.attrs['Nmesh'] != np.atleast_1d(Nmesh)):
        raise ValueError("mismatched Nmesh between argument and source; "
                         "resample by passing Nmesh to to_mesh()")
    return source


def _lattice_axes(pm, kind):
    """Integer frequency ranges spanned by each mesh axis, plus the
    per-axis physical unit. For ``complex`` the last axis is the
    hermitian-compressed non-negative half."""
    Nmesh = np.asarray(pm.Nmesh, dtype=int)
    Box = np.asarray(pm.BoxSize, dtype='f8')
    axes, units = [], []
    for ax, n in enumerate(Nmesh):
        n = int(n)
        if kind == 'complex':
            units.append(2 * np.pi / Box[ax])
            freq = (np.arange(n // 2 + 1) if ax == 2
                    else np.fft.fftfreq(n, 1.0 / n))
        elif kind == 'real':
            # min-image separation coordinates of the correlation
            # field (the FFTCorr dr=0 case; reference fftcorr.py:171)
            units.append(Box[ax] / n)
            freq = np.fft.fftfreq(n, 1.0 / n)
        else:
            raise ValueError("kind must be 'complex' or 'real'")
        axes.append(freq.astype('i8'))
    return axes, np.asarray(units)


def _edges_from_centers(fx, xmax, fine):
    """Midpoint edges around sorted unique centers (dedup with a fine
    quantum against round-off survivors)."""
    iy = np.round(fx / fine).astype(np.int64)
    _, ind = np.unique(iy, return_index=True)
    fx = fx[ind]
    fx = fx[fx < xmax]
    width = np.diff(fx)
    edges = fx.copy()
    edges[1:] -= width * 0.5
    edges = np.append(edges, [fx[-1] + width[-1] * 0.5])
    edges[0] = 0
    return edges, fx


def _find_unique_edges(pm, xmax, kind='complex'):
    """Bin edges hitting each unique coordinate modulus (the dk=0 mode;
    same capability as the reference, fftpower.py:732-769).

    For a cubic mesh (the common case) the moduli live on an exact
    integer lattice: |x|^2 = unit^2 * (ix^2 + iy^2 + iz^2) with
    ix^2+iy^2+iz^2 <= 3 (N/2)^2, so a dense presence histogram over
    integer norms enumerates EVERY unique modulus with no size cap and
    exact centers — at any Nmesh (the former device ``jnp.unique`` with
    a 2^20 cap silently dropped edges at Nmesh >= 1024, round-2 VERDICT
    weak #5). Anisotropic meshes fall back to a chunked quantize+unique
    merge that also has no cap.
    """
    axes, units = _lattice_axes(pm, kind)
    Nmesh = np.asarray(pm.Nmesh, dtype=int)
    cubic = (Nmesh == Nmesh[0]).all() and np.allclose(units, units[0])

    if cubic:
        unit = float(units[0])
        half = int(Nmesh[0]) // 2
        smax = 3 * half * half
        present = np.zeros(smax + 1, dtype=bool)
        sq12 = (axes[1][:, None] ** 2 + axes[2][None, :] ** 2).reshape(-1)
        rows = max(1, (1 << 23) // sq12.size)
        for lo in range(0, axes[0].size, rows):
            blk = axes[0][lo:lo + rows, None] ** 2 + sq12[None, :]
            present[np.unique(blk)] = True
        fx = unit * np.sqrt(np.flatnonzero(present).astype('f8'))
        return _edges_from_centers(fx, xmax, unit * 1e-5)

    # anisotropic: quantized-float uniques, merged chunkwise on host
    # keeping each bin's first-occurrence float (the centers stay
    # exact, not re-quantized)
    quantum = units.min() * 0.05
    c1 = (units[1] * axes[1][:, None]) ** 2 + \
        (units[2] * axes[2][None, :]) ** 2
    c1 = c1.reshape(-1)
    rows = max(1, (1 << 23) // c1.size)
    seen_q = np.empty(0, dtype='i8')
    seen_x = np.empty(0, dtype='f8')
    for lo in range(0, axes[0].size, rows):
        blk = ((units[0] * axes[0][lo:lo + rows, None]) ** 2
               + c1[None, :]).reshape(-1)
        q = (np.sqrt(blk) / quantum + 0.5).astype('i8')
        seen_q = np.concatenate([seen_q, q])
        seen_x = np.concatenate([seen_x, np.sqrt(blk)])
        # keep first occurrence per quantized value (np.unique
        # return_index points at first occurrences)
        _, first = np.unique(seen_q, return_index=True)
        seen_q, seen_x = seen_q[first], seen_x[first]
    fx = np.sort(seen_x)
    return _edges_from_centers(fx, xmax, units.min() * 1e-5)


class FFTBase(object):
    """Shared machinery for periodic-box FFT algorithms (reference
    fftpower.py:12-143): source casting, meta-data, 3-D power, JSON
    persistence."""

    def __init__(self, first, second, Nmesh, BoxSize):
        first = _cast_source(first, Nmesh=Nmesh, BoxSize=BoxSize)
        if second is not None:
            second = _cast_source(second, Nmesh=Nmesh, BoxSize=BoxSize)
        else:
            second = first
        self.first = first
        self.second = second
        self.comm = first.comm

        if not np.array_equal(first.attrs['BoxSize'],
                              second.attrs['BoxSize']):
            raise ValueError("BoxSize mismatch between sources")

        self.attrs = {}
        self.attrs['Nmesh'] = first.attrs['Nmesh'].copy()
        self.attrs['BoxSize'] = first.attrs['BoxSize'].copy()
        self.attrs.update(zip(['Lx', 'Ly', 'Lz'], self.attrs['BoxSize']))
        self.attrs['volume'] = self.attrs['BoxSize'].prod()

    def _compute_3d_power(self, first, second):
        """p3d = c1 * conj(c2) * V with the DC mode cleared (reference
        fftpower.py:91-143)."""
        attrs = dict(self.attrs)
        c1 = first.compute(mode='complex', Nmesh=self.attrs['Nmesh'])
        c2 = c1 if first is second else \
            second.compute(mode='complex', Nmesh=self.attrs['Nmesh'])

        p3d = c1.value * jnp.conj(c2.value)
        # clear the DC mode (transposed layout: [0,0,0] is k=0)
        p3d = p3d.at[0, 0, 0].set(0.0)
        p3d = p3d * self.attrs['BoxSize'].prod()

        N1 = c1.attrs.get('N', 0)
        N2 = c2.attrs.get('N', 0)
        attrs.update(N1=N1, N2=N2)
        Pshot = 0
        if self.first is self.second:
            Pshot = c1.attrs.get('shotnoise', 0)
        attrs['shotnoise'] = Pshot
        return Field(p3d, c1.pm, 'complex'), attrs

    def save(self, output):
        with open(output, 'w') as ff:
            json.dump(self.__getstate__(), ff, cls=JSONEncoder)

    @classmethod
    def load(cls, output, comm=None):
        with open(output, 'r') as ff:
            state = json.load(ff, cls=JSONDecoder)
        self = object.__new__(cls)
        self.__setstate__(state)
        self.comm = comm
        return self


class FFTPower(FFTBase):
    """P(k), P(k,mu) and multipoles P_ell(k) in a periodic box.

    API and semantics mirror the reference's FFTPower
    (algorithms/fftpower.py:146-359); results land in
    :attr:`power` / :attr:`poles` BinnedStatistics.
    """

    logger = logging.getLogger('FFTPower')

    def __init__(self, first, mode, Nmesh=None, BoxSize=None, second=None,
                 los=[0, 0, 1], Nmu=5, dk=None, kmin=0., kmax=None,
                 poles=[]):
        if mode not in ['1d', '2d']:
            raise ValueError("mode must be '1d' or '2d'")
        if poles is None:
            poles = []
        if np.isscalar(los) or len(los) != 3:
            raise ValueError("line-of-sight must be a 3-vector")
        if not np.allclose(np.dot(los, los), 1.0, rtol=1e-5):
            raise ValueError("line-of-sight must be a unit vector")

        FFTBase.__init__(self, first, second, Nmesh, BoxSize)

        self.attrs['mode'] = mode
        self.attrs['los'] = los
        self.attrs['Nmu'] = Nmu
        self.attrs['poles'] = poles
        if dk is None:
            dk = 2 * np.pi / self.attrs['BoxSize'].min()
        self.attrs['dk'] = dk
        self.attrs['kmin'] = kmin
        self.attrs['kmax'] = kmax

        with span_eager('fftpower.run', mode=mode,
                        nmesh=int(self.attrs['Nmesh'][0])):
            self.power, self.poles = self.run()
        self.attrs.update(self.power.attrs)

    def run(self):
        if self.attrs['mode'] == '1d':
            self.attrs['Nmu'] = 1

        y3d, attrs = self._compute_3d_power(self.first, self.second)

        dk = self.attrs['dk']
        kmin = self.attrs['kmin']
        kmax = self.attrs['kmax']
        if kmax is None:
            kmax = (np.pi * y3d.pm.Nmesh.min()
                    / y3d.pm.BoxSize.max() + dk / 2)

        if dk > 0:
            kedges = np.arange(kmin, kmax, dk)
            kcoords = None
        else:
            kedges, kcoords = _find_unique_edges(y3d.pm, kmax)

        muedges = np.linspace(-1, 1, self.attrs['Nmu'] + 1, endpoint=True)
        edges = [kedges, muedges]
        coords = [kcoords, None]
        result, pole_result = project_to_basis(
            y3d, edges, poles=self.attrs['poles'], los=self.attrs['los'])

        # package into structured arrays (reference run(), :317-334)
        if self.attrs['mode'] == '1d':
            cols = ['k', 'power', 'modes']
            icols = [0, 2, 3]
            edges = edges[0:1]
            coords = coords[0:1]
        else:
            cols = ['k', 'mu', 'power', 'modes']
            icols = [0, 1, 2, 3]

        dtype = np.dtype([(name, result[icol].dtype.str)
                          for icol, name in zip(icols, cols)])
        power = np.squeeze(np.empty(result[0].shape, dtype=dtype))
        for icol, col in zip(icols, cols):
            power[col][:] = np.squeeze(result[icol])

        poles = None
        if pole_result is not None:
            k, pole_arr, N = pole_result
            cols = ['k'] + ['power_%d' % l for l in self.attrs['poles']] \
                + ['modes']
            vals = [k] + [p for p in pole_arr] + [N]
            dtype = np.dtype([(name, vals[i].dtype.str)
                              for i, name in enumerate(cols)])
            poles = np.empty(vals[0].shape, dtype=dtype)
            for i, col in enumerate(cols):
                poles[col][:] = vals[i]

        return self._make_datasets(edges, poles, power, coords, attrs)

    def _make_datasets(self, edges, poles, power, coords, attrs):
        if self.attrs['mode'] == '1d':
            power = BinnedStatistic(['k'], edges, power,
                                    fields_to_sum=['modes'],
                                    coords=coords, **attrs)
        else:
            power = BinnedStatistic(['k', 'mu'], edges, power,
                                    fields_to_sum=['modes'],
                                    coords=coords, **attrs)
        if poles is not None:
            poles = BinnedStatistic(['k'], [power.edges['k']], poles,
                                    fields_to_sum=['modes'],
                                    coords=[power.coords['k']], **attrs)
        return power, poles

    def __getstate__(self):
        return dict(power=self.power.__getstate__(),
                    poles=self.poles.__getstate__()
                    if self.poles is not None else None,
                    attrs=self.attrs)

    def __setstate__(self, state):
        self.attrs = state['attrs']
        self.power = BinnedStatistic.from_state(state['power'])
        self.poles = BinnedStatistic.from_state(state['poles']) \
            if state['poles'] is not None else None


class ProjectedFFTPower(FFTBase):
    """Power spectrum of a field projected over a subset of axes (1d or
    2d maps; same capability as the reference's ProjectedFFTPower,
    fftpower.py:361-505).

    TPU design: the projection is a sum-reduction over the dropped axes
    of the sharded 3-D field, executed on device (GSPMD inserts the
    cross-device reduction for a slab-sharded mesh — no host gather of
    the cube). The projected map is tiny relative to the mesh, so its
    rFFT and the k-binning run in the same jitted program on one
    device; only the final (nbin,) histograms reach the host.
    """

    logger = logging.getLogger('ProjectedFFTPower')

    def __init__(self, first, Nmesh=None, BoxSize=None, second=None,
                 axes=(0, 1), dk=None, kmin=0.):
        FFTBase.__init__(self, first, second, Nmesh, BoxSize)
        if len(axes) not in (1, 2):
            raise ValueError("axes must have length 1 or 2")
        if dk is None:
            dk = 2 * np.pi / self.attrs['BoxSize'].min()
        self.attrs['dk'] = dk
        self.attrs['kmin'] = kmin
        self.attrs['axes'] = list(axes)
        self.run()

    def _map_geometry(self):
        """Host-side constants describing the projected map's rfft
        spectrum: (wavenumber magnitude, half-spectrum weights, bin
        edges, bin ids). All have the spectrum's (small) shape."""
        axes = list(self.attrs['axes'])
        dims = [int(self.attrs['Nmesh'][i]) for i in axes]
        lens = [float(self.attrs['BoxSize'][i]) for i in axes]
        nd = len(dims)

        spec_shape = tuple(dims[:-1]) + (dims[-1] // 2 + 1,)
        kk = np.zeros(spec_shape, dtype='f8')
        for j in range(nd):
            kfun = 2 * np.pi / lens[j]
            if j == nd - 1:
                freq = np.arange(spec_shape[-1], dtype='f8')
            else:
                freq = np.fft.fftfreq(dims[j], d=1.0 / dims[j])
            bshape = [1] * nd
            bshape[j] = freq.size
            kk = kk + (freq * kfun).reshape(bshape) ** 2
        kmag = np.sqrt(kk)

        # the rfft keeps the non-negative half of the last axis: every
        # plane except iz=0 (and the Nyquist plane for even N) stands
        # for a conjugate pair and counts twice
        wgt = np.full(spec_shape, 2.0)
        wgt[..., 0] = 1.0
        if dims[-1] % 2 == 0:
            wgt[..., -1] = 1.0

        kedges = np.arange(
            self.attrs['kmin'],
            np.pi * min(dims) / max(lens) + self.attrs['dk'] / 2,
            self.attrs['dk'])
        binid = np.digitize(kmag.reshape(-1), kedges)
        return kmag, wgt, kedges, binid

    def run(self):
        axes = list(self.attrs['axes'])
        Nmesh = self.attrs['Nmesh']
        dropped = tuple(i for i in range(3) if i not in axes)
        # sum over dropped axes keeps the survivors in index order;
        # permute to the user's requested axis order
        survivors = sorted(axes)
        perm = tuple(survivors.index(a) for a in axes)
        inv_norm = 1.0 / float(Nmesh.prod())

        kmag, wgt, kedges, binid = self._map_geometry()
        nb = len(kedges) + 1

        f1 = self.first.compute(Nmesh=Nmesh, mode='real')
        distinct = self.first is not self.second
        f2 = self.second.compute(Nmesh=Nmesh, mode='real') \
            if distinct else f1

        wgt_j = jnp.asarray(wgt.reshape(-1))
        kw_j = jnp.asarray((wgt * kmag).reshape(-1))
        bin_j = jnp.asarray(binid)

        def _pipeline(v1, v2):
            m1 = jnp.transpose(v1.sum(axis=dropped), perm)
            s1 = jnp.fft.rfftn(m1) * inv_norm
            if distinct:
                m2 = jnp.transpose(v2.sum(axis=dropped), perm)
                s2 = jnp.fft.rfftn(m2) * inv_norm
            else:
                s2 = s1
            spec = s1 * jnp.conj(s2)
            spec = spec.reshape(-1).at[0].set(0.0)  # clear DC
            ksum = jnp.bincount(bin_j, weights=kw_j, length=nb)
            nsum = jnp.bincount(bin_j, weights=wgt_j, length=nb)
            psum_re = jnp.bincount(bin_j, weights=spec.real * wgt_j,
                                   length=nb)
            psum_im = jnp.bincount(bin_j, weights=spec.imag * wgt_j,
                                   length=nb)
            return ksum, nsum, psum_re, psum_im

        ksum, nsum, psum_re, psum_im = (
            np.asarray(a, dtype='f8') for a in
            instrumented_jit(_pipeline, label='fftpower.projected')(
                f1.value, f2.value))

        area = float(np.prod([self.attrs['BoxSize'][i] for i in axes]))
        power = np.empty(len(kedges) - 1, dtype=[
            ('k', 'f8'), ('power', 'c16'), ('modes', 'f8')])
        with np.errstate(invalid='ignore', divide='ignore'):
            inner = slice(1, -1)
            power['k'] = (ksum / nsum)[inner]
            power['power'] = ((psum_re + 1j * psum_im) / nsum)[inner] \
                * area
            power['modes'] = nsum[inner]

        self.edges = kedges
        self.power = BinnedStatistic(['k'], [kedges], power,
                                     fields_to_sum=['modes'], **self.attrs)

    def __getstate__(self):
        return dict(edges=self.edges, power=self.power.data,
                    attrs=self.attrs)

    def __setstate__(self, state):
        self.attrs = state['attrs']
        self.edges = state['edges']
        self.power = BinnedStatistic(['k'], [self.edges], state['power'])
