"""FFT-based power spectrum estimators for periodic boxes.

Reference: ``nbodykit/algorithms/fftpower.py`` (FFTBase :12, FFTPower
:146, ProjectedFFTPower :361, project_to_basis :507). Capability parity:

- P(k) / P(k,mu) / multipoles P_ell(k) with the same binning semantics
  (under/overflow bins, half-open mu bins with an inclusive last bin,
  hermitian double-count weights, Nyquist planes counted once);
- dk=0 "unique edges" mode; save/load via JSON.

TPU redesign: the 3-D power and its (k, mu, ell) reduction run as one
jitted XLA program over the sharded transposed complex field — digitize
+ Legendre recurrence + weighted bincounts replace the reference's
rank-local slab loop (HOT LOOP 2 of SURVEY.md §3.1); means/packaging
happen on host with numpy (small arrays).
"""

import json
import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..base.catalog import CatalogSourceBase
from ..base.mesh import MeshSource, Field, FieldMesh
from ..binned_statistic import BinnedStatistic
from ..utils import JSONEncoder, JSONDecoder, as_numpy


def _legendre_all(ells, mu):
    """Evaluate Legendre P_ell(mu) for each ell in ``ells`` via the
    recurrence (jit-friendly; no scipy)."""
    lmax = max(ells) if ells else 0
    P_prev = jnp.ones_like(mu)           # P_0
    out = {0: P_prev}
    if lmax >= 1:
        P_cur = mu                       # P_1
        out[1] = P_cur
        for n in range(1, lmax):
            P_next = ((2 * n + 1) * mu * P_cur - n * P_prev) / (n + 1)
            P_prev, P_cur = P_cur, P_next
            out[n + 1] = P_cur
    return [out[ell] for ell in ells]


# elements per slab chunk of the binning reduction (patchable so tests
# can exercise the chunked path on small meshes)
_BIN_CHUNK_ELEMENTS = 1 << 22


def project_to_basis(y3d, edges, los=[0, 0, 1], poles=[]):
    """Bin a 3-D statistic into (x, mu) bins and optional multipoles.

    Parameters
    ----------
    y3d : Field — either a transposed hermitian-compressed complex field
        (binned in k) or a real field (binned in separation r, fftfreq
        ordering)
    edges : [xedges, muedges]
    los : unit line-of-sight vector
    poles : list of int multipoles

    Returns
    -------
    (xmean_2d, mumean_2d, y2d, N_2d), (xmean_1d, poles, N_1d) or None

    Semantics mirror the reference's project_to_basis
    (algorithms/fftpower.py:507-701): digitize against squared x edges,
    hermitian weights double-count kz>0 (excluding the Nyquist plane),
    odd multipoles keep 2i*Im, even keep 2*Re on the doubled modes.
    """
    pm = y3d.pm
    # a complex field with the full (uncompressed) kz axis is a c2c
    # spectrum: all modes present, no hermitian double-counting
    full_complex = (y3d.kind == 'complex'
                    and y3d.shape[2] == int(pm.Nmesh[2]))
    hermitian = (y3d.kind == 'complex') and not full_complex
    xedges, muedges = edges
    Nx = len(xedges) - 1
    Nmu = len(muedges) - 1

    do_poles = len(poles) > 0
    _poles = sorted(set([0]) | set(poles))
    Nell = len(_poles)
    ell_idx = [_poles.index(l) for l in poles]
    if any(ell < 0 for ell in _poles):
        raise ValueError("multipole numbers must be non-negative integers")

    nbins = (Nx + 2) * (Nmu + 2)

    if hermitian or full_complex:
        kx, ky, kz = pm.k_list(dtype=jnp.float64, full=full_complex)
        coords = [kx * los[0], ky * los[1], kz * los[2]]
        x2fac = [kx ** 2, ky ** 2, kz ** 2]
        if full_complex:
            w_b = jnp.ones((1, 1, 1), dtype=jnp.float64)
        else:
            w_b = pm.hermitian_weights(dtype=jnp.float64)  # (1,1,nz)
    else:
        # real field: separation coordinates in fftfreq ordering
        N0, N1, N2 = pm.shape_real
        L = pm.BoxSize
        rx = (jnp.fft.fftfreq(N0, d=1.0 / N0) * (L[0] / N0)
              ).reshape(N0, 1, 1)
        ry = (jnp.fft.fftfreq(N1, d=1.0 / N1) * (L[1] / N1)
              ).reshape(1, N1, 1)
        rz = (jnp.fft.fftfreq(N2, d=1.0 / N2) * (L[2] / N2)
              ).reshape(1, 1, N2)
        coords = [rx * los[0], ry * los[1], rz * los[2]]
        x2fac = [rx ** 2, ry ** 2, rz ** 2]
        w_b = jnp.ones((1, 1, 1), dtype=jnp.float64)

    x2edges = jnp.asarray(np.asarray(xedges, dtype='f8') ** 2)
    muedges_j = jnp.asarray(np.asarray(muedges, dtype='f8'))

    value = y3d.value
    is_cplx = jnp.iscomplexobj(value)

    # slab-chunk the reduction over the leading axis so no full-mesh
    # f64 temporary (x2 / mu / legendre / digitize) is ever live at
    # once — at Nmesh >= 1024 the unchunked version needs several
    # multi-GB buffers (round-1 VERDICT weak #6). Chunking needs an
    # exact row split and a single-device mesh (a sharded leading axis
    # stays on the fused whole-array path, which GSPMD shards).
    from ..parallel.runtime import mesh_size
    S0, S1, S2 = (int(s) for s in value.shape)
    target_rows = max(1, _BIN_CHUNK_ELEMENTS // max(1, S1 * S2))
    rows = min(S0, target_rows)
    while S0 % rows:
        rows -= 1
    nch = S0 // rows
    try:
        single = mesh_size(getattr(pm, 'comm', None)) == 1
    except Exception:
        single = True
    chunked = single and nch > 1
    if not chunked:
        rows = S0

    def slice0(a, i):
        """Slice the leading axis of a broadcastable factor. Whether a
        factor varies along axis 0 depends on the layout (transposed
        complex: ky leads; real: rx leads) — size-1 axes pass through."""
        if a.shape[0] == 1:
            return a
        return jax.lax.dynamic_slice_in_dim(a, i * rows, rows, 0)

    from ..ops.histogram import hist2d_weighted

    def chunk_hists(v_c, i):
        """All weighted histograms of one leading-axis slab."""
        x2 = sum(slice0(f, i) for f in x2fac)
        xnorm = jnp.sqrt(x2)
        mudot = sum(slice0(c, i) for c in coords)
        mu = jnp.where(xnorm == 0, 0.0,
                       mudot / jnp.where(xnorm == 0, 1.0, xnorm))
        shape = v_c.shape
        dig_x = jnp.digitize(
            jnp.broadcast_to(x2, shape).reshape(-1), x2edges)
        dig_mu = jnp.digitize(
            jnp.broadcast_to(mu, shape).reshape(-1), muedges_j)

        wf = jnp.broadcast_to(w_b, shape).reshape(-1)
        nonsing = (wf == 2.0)
        xw = jnp.broadcast_to(xnorm, shape).reshape(-1) * wf
        muw = jnp.broadcast_to(mu, shape).reshape(-1) * wf

        streams = [xw, muw, wf]
        legs = _legendre_all(_poles, mu)
        vre = v_c.real.astype(jnp.float64).reshape(-1)
        vim = (v_c.imag.astype(jnp.float64).reshape(-1)
               if is_cplx else None)
        for iell, ell in enumerate(_poles):
            leg = jnp.broadcast_to(legs[iell], shape).reshape(-1)
            yre = leg * vre
            yim = leg * vim if is_cplx else None
            if hermitian:
                if ell % 2:   # odd: real parts cancel between +k/-k
                    yre = jnp.where(nonsing, 0.0, yre)
                    yim = jnp.where(nonsing, 2.0 * yim, yim)
                else:         # even: imaginary parts cancel
                    yre = jnp.where(nonsing, 2.0 * yre, yre)
                    if is_cplx:
                        yim = jnp.where(nonsing, 0.0, yim)
            fac = (2.0 * ell + 1.0)
            streams.append(fac * yre)
            if is_cplx:
                streams.append(fac * yim)
        return hist2d_weighted(dig_x, dig_mu, streams,
                               Nx + 2, Nmu + 2)

    nstreams = 3 + Nell * (2 if is_cplx else 1)

    @jax.jit
    def _bin(value):
        if not chunked:
            hs = chunk_hists(value, 0)
        else:
            def body(i, acc):
                hs_c = chunk_hists(
                    jax.lax.dynamic_slice_in_dim(value, i * rows,
                                                 rows, 0), i)
                return [a + h for a, h in zip(acc, hs_c)]
            init = [jnp.zeros((Nx + 2, Nmu + 2), jnp.float64)
                    for _ in range(nstreams)]
            hs = jax.lax.fori_loop(0, nch, body, init)
        xsum, musum, Nsum = hs[0], hs[1], hs[2]
        ys_re, ys_im = [], []
        k = 3
        for _ in _poles:
            ys_re.append(hs[k]); k += 1
            if is_cplx:
                ys_im.append(hs[k]); k += 1
            else:
                ys_im.append(jnp.zeros_like(hs[0]))
        return (xsum.reshape(-1), musum.reshape(-1), Nsum.reshape(-1),
                jnp.stack([y.reshape(-1) for y in ys_re]),
                jnp.stack([y.reshape(-1) for y in ys_im]))

    xsum, musum, Nsum, ys_re, ys_im = _bin(value)

    # host-side: small (Nell, Nx+2, Nmu+2) arrays (np.array: writable copy)
    xsum = np.array(xsum).reshape(Nx + 2, Nmu + 2)
    musum = np.array(musum).reshape(Nx + 2, Nmu + 2)
    Nsum = np.array(Nsum).reshape(Nx + 2, Nmu + 2)
    ysum = (np.array(ys_re) + 1j * np.array(ys_im)
            ).reshape(Nell, Nx + 2, Nmu + 2)
    if not jnp.iscomplexobj(value):
        ysum = ysum.real

    # fold the internal mu == 1 bin into the last visible bin
    xsum[:, -2] += xsum[:, -1]
    musum[:, -2] += musum[:, -1]
    Nsum[:, -2] += Nsum[:, -1]
    ysum[..., -2] += ysum[..., -1]

    sl = slice(1, -1)
    with np.errstate(invalid='ignore', divide='ignore'):
        y2d = (ysum[0] / Nsum)[sl, sl]
        xmean_2d = (xsum / Nsum)[sl, sl]
        mumean_2d = (musum / Nsum)[sl, sl]
        N_2d = Nsum[sl, sl]

        pole_result = None
        if do_poles:
            N_1d = Nsum[sl, sl].sum(axis=-1)
            xmean_1d = xsum[sl, sl].sum(axis=-1) / N_1d
            pole_arr = ysum[:, sl, sl].sum(axis=-1) / N_1d
            pole_arr = pole_arr[ell_idx, ...]
            pole_result = (xmean_1d, pole_arr, N_1d)

    return (xmean_2d, mumean_2d, y2d, N_2d), pole_result


def _cast_source(source, BoxSize, Nmesh):
    """Coerce input to a MeshSource (reference fftpower.py:703-730)."""
    if isinstance(source, Field):
        source = FieldMesh(source)
    elif isinstance(source, CatalogSourceBase) and \
            not isinstance(source, MeshSource):
        source = source.to_mesh(BoxSize=BoxSize, Nmesh=Nmesh, dtype='f8',
                                compensated=True)
    if not isinstance(source, MeshSource):
        raise TypeError("unknown source type for FFT algorithm: %s"
                        % type(source))
    if BoxSize is not None and np.any(
            source.attrs['BoxSize'] != np.atleast_1d(BoxSize)):
        raise ValueError("mismatched BoxSize between argument and source")
    if Nmesh is not None and np.any(
            source.attrs['Nmesh'] != np.atleast_1d(Nmesh)):
        raise ValueError("mismatched Nmesh between argument and source; "
                         "resample by passing Nmesh to to_mesh()")
    return source


def _find_unique_edges(pm, xmax, kind='complex'):
    """Bin edges hitting each unique coordinate modulus (the dk=0 mode,
    reference fftpower.py:732-769). Computed on device via integer
    binning + unique, then fetched (small)."""
    if kind == 'complex':
        coords = pm.k_list(dtype=jnp.float64)
        x0 = 2 * np.pi / pm.BoxSize
    elif kind == 'real':
        # min-image separation coordinates of the correlation field
        # (the FFTCorr dr=0 case; reference fftcorr.py:171 passing
        # RealField.x into fftpower.py:732)
        coords = []
        for ax, (n, h) in enumerate(zip(pm.Nmesh, pm.cellsize)):
            shape = [1, 1, 1]
            shape[ax] = int(n)
            xi = jnp.fft.fftfreq(int(n), d=1.0 / int(n)).astype(
                jnp.float64) * float(h)
            coords.append(xi.reshape(shape))
        x0 = np.asarray(pm.cellsize, dtype='f8')
    else:
        raise ValueError("kind must be 'complex' or 'real'")
    x2 = sum(c ** 2 for c in coords).reshape(-1)
    binning = (x0.min() * 0.05) ** 2
    # unique via integer quantization, KEEPING the original float value
    # of each bin's first occurrence (reference find_unique_local,
    # fftpower.py:743-749) — the centers are exact, not re-quantized
    ix2 = (x2 / binning + 0.5).astype(jnp.int64)
    vals, idx = jnp.unique(ix2, return_index=True,
                           size=min(x2.size, 1 << 20), fill_value=-1)
    # jnp.unique pads `idx` with 0 (not fill_value); the number of real
    # uniques is how many `vals` slots escaped the -1 fill (x2 >= 0 so
    # every real quantized value is >= 0)
    nuniq = int(np.asarray((vals >= 0).sum()))
    idx = np.asarray(idx)[:nuniq]
    fx2 = np.asarray(x2[jnp.asarray(idx)], dtype='f8')
    fx = np.sort(np.sqrt(fx2))
    # dedup round-off survivors with a much finer quantum
    iy = np.round(fx / (x0.min() * 1e-5)).astype(np.int64)
    _, ind = np.unique(iy, return_index=True)
    fx = fx[ind]
    fx = fx[fx < xmax]
    width = np.diff(fx)
    edges = fx.copy()
    edges[1:] -= width * 0.5
    edges = np.append(edges, [fx[-1] + width[-1] * 0.5])
    edges[0] = 0
    return edges, fx


class FFTBase(object):
    """Shared machinery for periodic-box FFT algorithms (reference
    fftpower.py:12-143): source casting, meta-data, 3-D power, JSON
    persistence."""

    def __init__(self, first, second, Nmesh, BoxSize):
        first = _cast_source(first, Nmesh=Nmesh, BoxSize=BoxSize)
        if second is not None:
            second = _cast_source(second, Nmesh=Nmesh, BoxSize=BoxSize)
        else:
            second = first
        self.first = first
        self.second = second
        self.comm = first.comm

        if not np.array_equal(first.attrs['BoxSize'],
                              second.attrs['BoxSize']):
            raise ValueError("BoxSize mismatch between sources")

        self.attrs = {}
        self.attrs['Nmesh'] = first.attrs['Nmesh'].copy()
        self.attrs['BoxSize'] = first.attrs['BoxSize'].copy()
        self.attrs.update(zip(['Lx', 'Ly', 'Lz'], self.attrs['BoxSize']))
        self.attrs['volume'] = self.attrs['BoxSize'].prod()

    def _compute_3d_power(self, first, second):
        """p3d = c1 * conj(c2) * V with the DC mode cleared (reference
        fftpower.py:91-143)."""
        attrs = dict(self.attrs)
        c1 = first.compute(mode='complex', Nmesh=self.attrs['Nmesh'])
        c2 = c1 if first is second else \
            second.compute(mode='complex', Nmesh=self.attrs['Nmesh'])

        p3d = c1.value * jnp.conj(c2.value)
        # clear the DC mode (transposed layout: [0,0,0] is k=0)
        p3d = p3d.at[0, 0, 0].set(0.0)
        p3d = p3d * self.attrs['BoxSize'].prod()

        N1 = c1.attrs.get('N', 0)
        N2 = c2.attrs.get('N', 0)
        attrs.update(N1=N1, N2=N2)
        Pshot = 0
        if self.first is self.second:
            Pshot = c1.attrs.get('shotnoise', 0)
        attrs['shotnoise'] = Pshot
        return Field(p3d, c1.pm, 'complex'), attrs

    def save(self, output):
        with open(output, 'w') as ff:
            json.dump(self.__getstate__(), ff, cls=JSONEncoder)

    @classmethod
    def load(cls, output, comm=None):
        with open(output, 'r') as ff:
            state = json.load(ff, cls=JSONDecoder)
        self = object.__new__(cls)
        self.__setstate__(state)
        self.comm = comm
        return self


class FFTPower(FFTBase):
    """P(k), P(k,mu) and multipoles P_ell(k) in a periodic box.

    API and semantics mirror the reference's FFTPower
    (algorithms/fftpower.py:146-359); results land in
    :attr:`power` / :attr:`poles` BinnedStatistics.
    """

    logger = logging.getLogger('FFTPower')

    def __init__(self, first, mode, Nmesh=None, BoxSize=None, second=None,
                 los=[0, 0, 1], Nmu=5, dk=None, kmin=0., kmax=None,
                 poles=[]):
        if mode not in ['1d', '2d']:
            raise ValueError("mode must be '1d' or '2d'")
        if poles is None:
            poles = []
        if np.isscalar(los) or len(los) != 3:
            raise ValueError("line-of-sight must be a 3-vector")
        if not np.allclose(np.dot(los, los), 1.0, rtol=1e-5):
            raise ValueError("line-of-sight must be a unit vector")

        FFTBase.__init__(self, first, second, Nmesh, BoxSize)

        self.attrs['mode'] = mode
        self.attrs['los'] = los
        self.attrs['Nmu'] = Nmu
        self.attrs['poles'] = poles
        if dk is None:
            dk = 2 * np.pi / self.attrs['BoxSize'].min()
        self.attrs['dk'] = dk
        self.attrs['kmin'] = kmin
        self.attrs['kmax'] = kmax

        self.power, self.poles = self.run()
        self.attrs.update(self.power.attrs)

    def run(self):
        if self.attrs['mode'] == '1d':
            self.attrs['Nmu'] = 1

        y3d, attrs = self._compute_3d_power(self.first, self.second)

        dk = self.attrs['dk']
        kmin = self.attrs['kmin']
        kmax = self.attrs['kmax']
        if kmax is None:
            kmax = (np.pi * y3d.pm.Nmesh.min()
                    / y3d.pm.BoxSize.max() + dk / 2)

        if dk > 0:
            kedges = np.arange(kmin, kmax, dk)
            kcoords = None
        else:
            kedges, kcoords = _find_unique_edges(y3d.pm, kmax)

        muedges = np.linspace(-1, 1, self.attrs['Nmu'] + 1, endpoint=True)
        edges = [kedges, muedges]
        coords = [kcoords, None]
        result, pole_result = project_to_basis(
            y3d, edges, poles=self.attrs['poles'], los=self.attrs['los'])

        # package into structured arrays (reference run(), :317-334)
        if self.attrs['mode'] == '1d':
            cols = ['k', 'power', 'modes']
            icols = [0, 2, 3]
            edges = edges[0:1]
            coords = coords[0:1]
        else:
            cols = ['k', 'mu', 'power', 'modes']
            icols = [0, 1, 2, 3]

        dtype = np.dtype([(name, result[icol].dtype.str)
                          for icol, name in zip(icols, cols)])
        power = np.squeeze(np.empty(result[0].shape, dtype=dtype))
        for icol, col in zip(icols, cols):
            power[col][:] = np.squeeze(result[icol])

        poles = None
        if pole_result is not None:
            k, pole_arr, N = pole_result
            cols = ['k'] + ['power_%d' % l for l in self.attrs['poles']] \
                + ['modes']
            vals = [k] + [p for p in pole_arr] + [N]
            dtype = np.dtype([(name, vals[i].dtype.str)
                              for i, name in enumerate(cols)])
            poles = np.empty(vals[0].shape, dtype=dtype)
            for i, col in enumerate(cols):
                poles[col][:] = vals[i]

        return self._make_datasets(edges, poles, power, coords, attrs)

    def _make_datasets(self, edges, poles, power, coords, attrs):
        if self.attrs['mode'] == '1d':
            power = BinnedStatistic(['k'], edges, power,
                                    fields_to_sum=['modes'],
                                    coords=coords, **attrs)
        else:
            power = BinnedStatistic(['k', 'mu'], edges, power,
                                    fields_to_sum=['modes'],
                                    coords=coords, **attrs)
        if poles is not None:
            poles = BinnedStatistic(['k'], [power.edges['k']], poles,
                                    fields_to_sum=['modes'],
                                    coords=[power.coords['k']], **attrs)
        return power, poles

    def __getstate__(self):
        return dict(power=self.power.__getstate__(),
                    poles=self.poles.__getstate__()
                    if self.poles is not None else None,
                    attrs=self.attrs)

    def __setstate__(self, state):
        self.attrs = state['attrs']
        self.power = BinnedStatistic.from_state(state['power'])
        self.poles = BinnedStatistic.from_state(state['poles']) \
            if state['poles'] is not None else None


class ProjectedFFTPower(FFTBase):
    """Power spectrum of a field projected over a subset of axes (1d or
    2d maps; reference fftpower.py:361-505). The projected maps are
    small, so the FFT + binning run on host numpy after a distributed
    projection."""

    logger = logging.getLogger('ProjectedFFTPower')

    def __init__(self, first, Nmesh=None, BoxSize=None, second=None,
                 axes=(0, 1), dk=None, kmin=0.):
        FFTBase.__init__(self, first, second, Nmesh, BoxSize)
        if len(axes) not in (1, 2):
            raise ValueError("axes must have length 1 or 2")
        if dk is None:
            dk = 2 * np.pi / self.attrs['BoxSize'].min()
        self.attrs['dk'] = dk
        self.attrs['kmin'] = kmin
        self.attrs['axes'] = list(axes)
        self.run()

    def run(self):
        axes = list(self.attrs['axes'])
        Nmesh = self.attrs['Nmesh']
        BoxSize = self.attrs['BoxSize']

        r1 = self.first.compute(Nmesh=Nmesh, mode='real').preview(axes=axes)
        c1 = np.fft.rfftn(r1) / Nmesh.prod()
        if self.first is self.second:
            c2 = c1
        else:
            r2 = self.second.compute(Nmesh=Nmesh,
                                     mode='real').preview(axes=axes)
            c2 = np.fft.rfftn(r2) / Nmesh.prod()

        pk = c1 * c2.conj()
        pk.flat[0] = 0

        shape = np.array([Nmesh[i] for i in axes], dtype='int')
        boxsize = np.array([BoxSize[i] for i in axes])
        I = np.eye(len(shape), dtype='int') * -2 + 1
        k = [np.fft.fftfreq(N, 1. / (N * 2 * np.pi / L))[:pkshape]
             .reshape(kshape) for N, L, kshape, pkshape
             in zip(shape, boxsize, I, pk.shape)]
        kmag = sum(ki ** 2 for ki in k) ** 0.5

        W = np.full(pk.shape, 2.0, dtype='f4')
        W[..., 0] = 1.0
        W[..., -1] = 1.0

        dk = self.attrs['dk']
        kmin = self.attrs['kmin']
        kedges = np.arange(kmin, np.pi * shape.min() / boxsize.max()
                           + dk / 2, dk)

        xsum = np.zeros(len(kedges) + 1)
        Psum = np.zeros(len(kedges) + 1, dtype='complex128')
        Nsum = np.zeros(len(kedges) + 1)
        dig = np.digitize(kmag.flat, kedges)
        xsum.flat += np.bincount(dig, weights=(W * kmag).flat,
                                 minlength=xsum.size)
        Psum.real.flat += np.bincount(dig, weights=(W * pk.real).flat,
                                      minlength=xsum.size)
        Psum.imag.flat += np.bincount(dig, weights=(W * pk.imag).flat,
                                      minlength=xsum.size)
        Nsum.flat += np.bincount(dig, weights=W.flat, minlength=xsum.size)

        power = np.empty(len(kedges) - 1, dtype=[
            ('k', 'f8'), ('power', 'c16'), ('modes', 'f8')])
        with np.errstate(invalid='ignore', divide='ignore'):
            power['k'] = (xsum / Nsum)[1:-1]
            power['power'] = (Psum / Nsum)[1:-1] * boxsize.prod()
            power['modes'] = Nsum[1:-1]

        self.edges = kedges
        self.power = BinnedStatistic(['k'], [kedges], power,
                                     fields_to_sum=['modes'], **self.attrs)

    def __getstate__(self):
        return dict(edges=self.edges, power=self.power.data,
                    attrs=self.attrs)

    def __setstate__(self, state):
        self.attrs = state['attrs']
        self.edges = state['edges']
        self.power = BinnedStatistic(['k'], [self.edges], state['power'])
