"""Bispectrum B(k1, k2, k3) in a periodic box — the hybrid FFT/direct
higher-order estimator (ROADMAP item 2; docs/BISPECTRUM.md).

Two estimators of the same statistic, selected per shape-class by the
tuner (``bspec_method``), agreeing in their overlap k-band:

**FFT path** (low k) — the Scoccimarro triangle-count method.  With
the repo's forward-normalized transform (``pmesh.r2c`` divides by
Ntot, so ``c2r(c) = sum_k c_k e^{ikx}``), the per-shell filtered field

    delta_b(x) = c2r(delta_k * 1_{|q| in shell b})

turns the mesh-product sum into an exact sum over *closed* mode
triangles (closed mod Nmesh per axis — the aliased closure of the
discrete mesh):

    sum_x delta_1 delta_2 delta_3
        = Ntot * sum_{q1+q2+q3 = 0 (mod N)} delta_q1 delta_q2 delta_q3

and the matching product of unit-amplitude fields counts the same
triangles, so the Ntot cancels in the ratio:

    B(b1, b2, b3) = V^2 * sum_x(d1 d2 d3) / sum_x(I1 I2 I3),
    Ntri          = sum_x(I1 I2 I3) / Ntot

(the V^2 completing the repo's P(k) = V |delta_k|^2 convention,
fftpower._compute_3d_power).  The three c2r's per triangle stream
through ONE jitted program with the integer shell thresholds as traced
scalars — peak residency is 3 real fields + 1 complex, the
``memory_plan(workload='bispectrum')`` pricing model, NOT nbins
fields.

**Direct path** (high k; PAPERS.md 2005.01739) — exact mode sums

    delta(q) = (1/W) sum_j w_j exp(-i k_q . x_j)

via the dense pairwise blocks of :mod:`..ops.pairblock` (the MXU
shape), then host-side triangle combination over the enumerated
integer-lattice shells with *true* (unwrapped) closure.  No mesh, no
window, no aliasing — at high k this beats the FFT estimator's
resolution requirements outright; the per-platform crossover is
measured by the ``bspec`` tune space, never guessed.

Shell convention shared by both paths: bin ``b`` covers
``|q| in [b+1, b+2)`` lattice units of the fundamental
``kf = 2 pi / L`` (DC is excluded by construction), i.e.
``kedges = kf * arange(1, nbins + 2)``.  The k-bin masks digitize the
exact int32 lattice norms through the audited shell path of
:mod:`..ops.histogram`.
"""

import json
import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..base.catalog import CatalogSourceBase
from ..base.mesh import MeshSource
from ..binned_statistic import BinnedStatistic
from ..diagnostics import span_eager
from ..utils import JSONEncoder, JSONDecoder, as_numpy
from ..ops.histogram import lattice_shell_edges
from .fftpower import FFTBase


def shell_filtered_field(pm, cplx, lo2, hi2):
    """The per-shell filtered field ``delta_b(x) = c2r(cplx * mask)``
    with ``mask = 1_{lo2 <= |i|^2 < hi2}`` on the integer lattice —
    a full mesh-sized real field per call (the FFT path's dominant
    residency; lint/sizes.py prices it as such).

    ``lo2``/``hi2`` may be traced int32 scalars: the shell thresholds
    ride the jitted program as data, so every triangle reuses ONE
    compiled executable."""
    ix, iy, iz = pm.i_list_complex()
    isq = ix * ix + iy * iy + iz * iz
    mask = (isq >= lo2) & (isq < hi2)
    return pm.c2r(jnp.where(mask, cplx, 0))


def _make_triple_sum(pm):
    """One jitted ``(cplx, edges2) -> sum_x d1 d2 d3`` program:
    ``edges2`` is a (3, 2) int32 array of ``[lo2, hi2)`` shell
    thresholds.  Invoked once per (triangle, pass); the count pass
    feeds an all-ones spectrum (``c2r(mask) = I_b``)."""

    def triple(cplx, edges2):
        prod = None
        for t in range(3):
            d = shell_filtered_field(pm, cplx, edges2[t, 0],
                                     edges2[t, 1])
            prod = d if prod is None else prod * d
        return jnp.sum(prod)

    # one jitted program per run, reused 2x per triangle — the
    # recompile-per-call hazard does not apply
    return jax.jit(triple)   # nbkl: disable=NBK202


def triangle_bins(nbins):
    """Canonical (b1 <= b2 <= b3) shell triples whose k-intervals can
    close a triangle: ``kedges[b3] < kedges[b1+1] + kedges[b2+1]``
    (min third side below the sum of the max first two).  Off-list
    cells of the (nbins,)*3 result stay NaN."""
    out = []
    for i in range(nbins):
        for j in range(i, nbins):
            for l in range(j, nbins):
                if (l + 1) < (i + 2) + (j + 2):
                    out.append((i, j, l))
    return out


def _shell_edges2(nbins, BoxSize):
    """(nbins, 2) int32 ``[lo2, hi2)`` integer squared-norm thresholds
    of the unit-width shells, through the shared audited edge
    quantization."""
    kf = 2.0 * np.pi / float(np.min(BoxSize))
    kedges = kf * np.arange(1, nbins + 2)
    qe = lattice_shell_edges(kedges, kf)
    return np.stack([qe[:-1], qe[1:]], axis=1), kedges


def fft_bispectrum(pm, cplx, nbins):
    """The Scoccimarro estimator on a (possibly distributed) complex
    field: ``(B, ntri)`` as (nbins,)*3 host arrays, NaN where no
    closed triangle exists.  ``ntri`` is the ordered mod-N triangle
    count ``sum_x(I1 I2 I3) / Ntot``."""
    edges2, _ = _shell_edges2(nbins, pm.BoxSize)
    V = float(np.prod(pm.BoxSize))
    Ntot = float(pm.Ntot)
    triple = _make_triple_sum(pm)
    ones = jnp.ones(pm.shape_complex,
                    dtype=jnp.asarray(cplx).dtype)

    B = np.full((nbins,) * 3, np.nan, dtype='f8')
    ntri = np.full((nbins,) * 3, np.nan, dtype='f8')
    for (i, j, l) in triangle_bins(nbins):
        e = jnp.asarray(np.stack([edges2[i], edges2[j], edges2[l]]),
                        dtype=jnp.int32)
        S = float(triple(cplx, e))
        # the count is an integer by construction (closed-triangle
        # cardinality); snap off the c2r float rounding so both paths
        # report bit-identical ntri and share one normalization
        T = round(float(triple(ones, e)) / Ntot) * Ntot
        for perm in {(i, j, l), (i, l, j), (j, i, l), (j, l, i),
                     (l, i, j), (l, j, i)}:
            ntri[perm] = T / Ntot if T > 0 else np.nan
            B[perm] = V * V * S / T if T > 0 else np.nan
    return B, ntri


def shell_modes(nbins):
    """Host enumeration of the half-sphere integer lattice modes of
    the ``nbins`` unit-width shells: ``(qvecs, shell)`` with ``qvecs``
    (Nk, 3) int and ``shell`` (Nk,) in [0, nbins).  Exactly one of
    ``q``/``-q`` is listed (lexicographic half); the conjugate
    expansion is the caller's (``delta(-q) = conj(delta(q))``)."""
    M = nbins + 1
    r = np.arange(-M, M + 1)
    qx, qy, qz = np.meshgrid(r, r, r, indexing='ij')
    q = np.stack([qx, qy, qz], axis=-1).reshape(-1, 3)
    isq = (q.astype('i8') ** 2).sum(axis=1)
    shell = np.floor(np.sqrt(isq.astype('f8'))).astype('i8') - 1
    keep = (isq >= 1) & (shell < nbins)
    half = (q[:, 2] > 0) \
        | ((q[:, 2] == 0) & (q[:, 1] > 0)) \
        | ((q[:, 2] == 0) & (q[:, 1] == 0) & (q[:, 0] > 0))
    sel = keep & half
    return q[sel], shell[sel].astype('i8')


def _combine_triangles(q, shell, delta, nbins, chunk=512):
    """Host triangle combination of full-sphere direct modes with TRUE
    (unwrapped) closure ``q3 = -(q1 + q2)``: returns ``(S, cnt)`` with
    ``S[b1, b2, b3] = sum delta_q1 delta_q2 delta_q3`` over ordered
    closed triples and ``cnt`` their count.  Dense integer LUT lookup
    (q -> mode index, -1 outside) chunked over q1 rows."""
    M = int(np.abs(q).max())
    side = 2 * M + 1
    lut = np.full(side ** 3, -1, dtype='i8')
    flat = ((q[:, 0] + M) * side + (q[:, 1] + M)) * side + (q[:, 2] + M)
    lut[flat] = np.arange(q.shape[0])

    S = np.zeros((nbins,) * 3, dtype='c16')
    cnt = np.zeros((nbins,) * 3, dtype='f8')
    for b1 in range(nbins):
        i1 = np.flatnonzero(shell == b1)
        for b2 in range(nbins):
            i2 = np.flatnonzero(shell == b2)
            q2 = q[i2]
            d2 = delta[i2]
            for lo in range(0, i1.size, chunk):
                i1c = i1[lo:lo + chunk]
                q3 = -(q[i1c][:, None, :] + q2[None, :, :])
                inside = np.abs(q3).max(axis=-1) <= M
                f3 = ((q3[..., 0] + M) * side
                      + (q3[..., 1] + M)) * side + (q3[..., 2] + M)
                t = np.where(inside, lut[np.where(inside, f3, 0)], -1)
                valid = t >= 0
                s3 = np.where(valid, shell[np.where(valid, t, 0)], -1)
                prod = delta[i1c][:, None] * d2[None, :] \
                    * delta[np.where(valid, t, 0)]
                for b3 in range(nbins):
                    m = (s3 == b3)
                    S[b1, b2, b3] += prod[m].sum()
                    cnt[b1, b2, b3] += float(m.sum())
    return S, cnt


def direct_bispectrum(pos, w, BoxSize, nbins, tile=None, comm=None):
    """The blocked direct-summation estimator: exact per-mode sums via
    :func:`~nbodykit_tpu.ops.pairblock.pairblock_sum`, host triangle
    combination.  ``(B, ntri)`` as (nbins,)*3 host arrays, NaN where
    no closed (unwrapped) triangle exists."""
    from ..ops.pairblock import pairblock_sum, lattice_kvecs

    BoxSize = np.ones(3) * np.asarray(BoxSize, dtype='f8')
    V = float(np.prod(BoxSize))
    q_half, shell_half = shell_modes(nbins)
    kv = lattice_kvecs(q_half, BoxSize)
    modes = pairblock_sum(pos, w, kv, tile=tile, comm=comm)
    W = float(jnp.sum(jnp.asarray(w)))
    # complex device->host transfer rides real/imag pairs (the axon
    # TPU runtime does not implement complex transfers)
    d_half = as_numpy(modes) / W

    # conjugate expansion to the full sphere
    q = np.concatenate([q_half, -q_half])
    shell = np.concatenate([shell_half, shell_half])
    delta = np.concatenate([d_half, np.conj(d_half)])

    S, cnt = _combine_triangles(q, shell, delta, nbins)
    with np.errstate(invalid='ignore', divide='ignore'):
        B = np.where(cnt > 0, V * V * S.real / np.where(cnt > 0, cnt, 1),
                     np.nan)
    ntri = np.where(cnt > 0, cnt, np.nan)
    return B, ntri


class Bispectrum(FFTBase):
    """B(k1, k2, k3) on unit-width k shells in a periodic box.

    ``method`` is ``'fft'``, ``'direct'`` or ``'auto'`` — the latter
    resolved through the tuner
    (:func:`~nbodykit_tpu.tune.resolve.resolve_bispectrum`; cold cache
    defaults to ``'fft'``).  The direct path requires a catalog source
    (it sums over particles, not mesh cells); ``'auto'`` on a pure
    mesh source resolves to ``'fft'``.

    Results land in :attr:`B`, a ``BinnedStatistic`` over
    ``(k1, k2, k3)`` with fields ``B`` and ``ntri`` (NaN outside the
    closed-triangle region).
    """

    logger = logging.getLogger('Bispectrum')

    def __init__(self, source, nbins=4, Nmesh=None, BoxSize=None,
                 method='auto', tile=None):
        if method not in ('auto', 'fft', 'direct'):
            raise ValueError("method must be 'auto', 'fft' or "
                             "'direct'")
        nbins = int(nbins)
        if nbins < 1:
            raise ValueError("nbins must be >= 1")

        is_catalog = isinstance(source, CatalogSourceBase) and \
            not isinstance(source, MeshSource)
        if method == 'direct' and not is_catalog:
            raise ValueError("the direct bispectrum path sums over "
                             "particles; pass a catalog source")

        from ..parallel.runtime import mesh_size
        comm = getattr(source, 'comm', None)
        nproc = mesh_size(comm)
        npart = int(source.size) if is_catalog else None
        nmesh_q = None
        if Nmesh is not None:
            nmesh_q = int(np.max(np.atleast_1d(Nmesh)))
        elif 'Nmesh' in getattr(source, 'attrs', {}):
            nmesh_q = int(np.max(np.atleast_1d(
                source.attrs['Nmesh'])))

        if method == 'auto' or tile is None:
            from ..tune.resolve import resolve_bispectrum
            cfg = resolve_bispectrum(nmesh=nmesh_q, npart=npart,
                                     nproc=nproc)
            if method == 'auto':
                method = cfg['bspec_method']
            if tile is None:
                tile = cfg['pairblock_tile']
        if method == 'direct' and not is_catalog:
            method = 'fft'

        if method == 'direct':
            box = BoxSize if BoxSize is not None \
                else source.attrs['BoxSize']
            box = np.ones(3) * np.asarray(box, dtype='f8')
            self.first = self.second = source
            self.comm = comm
            self.attrs = {'Nmesh': np.atleast_1d(
                Nmesh if Nmesh is not None else 0),
                'BoxSize': box, 'volume': float(box.prod())}
            pos = jnp.asarray(source['Position'])
            w = jnp.asarray(source['Weight']) if 'Weight' in source \
                else jnp.ones(pos.shape[0], pos.dtype)
            with span_eager('bispectrum.run', method='direct',
                            nbins=nbins):
                B, ntri = direct_bispectrum(pos, w, box, nbins,
                                            tile=tile, comm=comm)
        else:
            FFTBase.__init__(self, source, None, Nmesh, BoxSize)
            c1 = self.first.compute(mode='complex',
                                    Nmesh=self.attrs['Nmesh'])
            with span_eager('bispectrum.run', method='fft',
                            nbins=nbins):
                B, ntri = fft_bispectrum(c1.pm, c1.value, nbins)
            box = np.asarray(self.attrs['BoxSize'], dtype='f8')

        _, kedges = _shell_edges2(nbins, box)
        self.attrs.update(nbins=nbins, method=method,
                          kf=float(2 * np.pi / box.min()))
        centers = 0.5 * (kedges[1:] + kedges[:-1])
        sh = (nbins,) * 3
        data = {
            'k1': np.broadcast_to(centers[:, None, None], sh).copy(),
            'k2': np.broadcast_to(centers[None, :, None], sh).copy(),
            'k3': np.broadcast_to(centers[None, None, :], sh).copy(),
            'B': B, 'ntri': ntri,
        }
        self.B = BinnedStatistic(['k1', 'k2', 'k3'], [kedges] * 3,
                                 data, fields_to_sum=['ntri'],
                                 **self.attrs)

    def __getstate__(self):
        return dict(B=self.B.__getstate__(), attrs=self.attrs)

    def __setstate__(self, state):
        self.attrs = state['attrs']
        self.B = BinnedStatistic.from_state(state['B'])
