"""KDDensity: a fast per-particle density proxy.

Reference: ``nbodykit/algorithms/kdtree.py:9`` — crude density from
nearest-neighbor distances (scipy cKDTree + domain ghosts there).
TPU redesign: neighbor *counts* within a kernel radius via the same
grid-hash sweep as FOF/pair counting, fully vectorized; the density
proxy is count / kernel volume.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..utils import as_numpy


class KDDensity(object):
    """Estimate a local density proxy for every object.

    Parameters
    ----------
    source : CatalogSource with Position and attrs['BoxSize']
    margin : float — kernel radius in units of the mean inter-particle
        separation (reference uses a margin-scaled proximity too)

    Attributes
    ----------
    density : (N,) density proxy (neighbors within the kernel / kernel
        volume), same normalization role as the reference's proxy.
    """

    logger = logging.getLogger('KDDensity')

    def __init__(self, source, margin=1.0):
        if 'Position' not in source:
            raise ValueError("source needs a Position column")
        self.comm = source.comm
        BoxSize = np.ones(3) * np.asarray(source.attrs['BoxSize'],
                                          dtype='f8')
        self.attrs = dict(margin=margin, BoxSize=BoxSize)

        pos = as_numpy(source['Position'])
        N = len(pos)
        mean_sep = (np.prod(BoxSize) / N) ** (1.0 / 3)
        r = margin * mean_sep
        self.attrs['kernel_radius'] = r

        from ..ops.gridhash import GridHash
        grid = GridHash(pos, BoxSize, r, periodic=True)
        r2 = r * r

        @jax.jit
        def neighbor_counts(p):
            ci = grid.cell_of(p)
            def body(total, j, valid, d, rr2):
                return total + jnp.where(valid & (rr2 <= r2), 1.0, 0.0)
            return grid.fold(p, ci, body, jnp.zeros(p.shape[0]))

        counts_per = neighbor_counts(jnp.asarray(pos))
        vol = 4.0 / 3 * np.pi * r ** 3
        self.density = counts_per / vol
