"""KDDensity: a fast per-particle density proxy.

Reference: ``nbodykit/algorithms/kdtree.py:9`` — crude density from
nearest-neighbor distances (scipy cKDTree + domain ghosts there;
GridND decompose at nbodykit/algorithms/kdtree.py:70-90). TPU
redesign: neighbor *counts* within a kernel radius via the same
grid-hash sweep as FOF/pair counting, fully vectorized; the density
proxy is count / kernel volume. With a device mesh active the sweep
runs domain-decomposed: particles route to x-slab owners with
both-side ghost copies within the kernel radius, each device sweeps
its slab in-graph, and per-particle counts route back to the global
order — no device ever holds the full particle set.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..utils import as_numpy


def _kdd_counts_dist(pos, box, r, mesh, periodic=True):
    """Per-particle neighbor counts within ``r``, domain-decomposed.

    pos : (N, 3) global sharded positions; box : (3,) floats;
    r : kernel radius. Returns (N,) f4 counts (self included), as a
    global sharded array in input order.
    """
    from jax.sharding import PartitionSpec as P
    from ..parallel.runtime import AXIS, shard_leading
    from ..parallel.domain import slab_route, scatter_reduce_by_index
    from ..ops.devicehash import DeviceGridHash

    N = int(pos.shape[0])
    box = np.asarray(box, dtype='f8')
    route, f, live = slab_route(pos, box, r, mesh, ghosts='both',
                                periodic=periodic, balance=True)
    gid = shard_leading(mesh, jnp.arange(N, dtype=jnp.int32))
    own = jnp.concatenate(
        [jnp.ones(N, bool)] + [jnp.zeros(N, bool)] * (f - 1))
    pos_f = jnp.concatenate([pos] * f)
    gid_f = jnp.concatenate([gid] * f)
    (pos_r, gid_r, own_r, live_r), ok, _ = route.exchange(
        [pos_f, gid_f, own, live])
    valid = ok & live_r
    r2 = float(r) ** 2

    def local(p, v, own_l):
        grid = DeviceGridHash(p, box, r, valid=v, periodic=periodic,
                              axis_name=AXIS)
        ci = grid.cell_of(grid.pos_s)
        own_s = own_l[grid.order] & grid.valid_s

        def body(total, j, okc, d, rr2):
            hit = okc & own_s & (rr2 <= r2)
            return total + jnp.where(hit, 1.0, 0.0)

        counts_s = grid.fold(grid.pos_s, ci, body,
                             jnp.zeros(p.shape[0], jnp.float32))
        # back to slot order
        return jnp.zeros(p.shape[0], jnp.float32).at[grid.order].set(
            counts_s)

    counts_r = jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(P(AXIS, None), P(AXIS), P(AXIS)),
        out_specs=P(AXIS)))(pos_r, valid, own_r)
    own_live = own_r & valid
    out = scatter_reduce_by_index(gid_r, counts_r, N, mesh, op='add',
                                  valid=own_live)
    return out[:N]


class KDDensity(object):
    """Estimate a local density proxy for every object.

    Parameters
    ----------
    source : CatalogSource with Position and attrs['BoxSize']
    margin : float — kernel radius in units of the mean inter-particle
        separation (reference uses a margin-scaled proximity too)

    Attributes
    ----------
    density : (N,) density proxy (neighbors within the kernel / kernel
        volume), same normalization role as the reference's proxy.
    """

    logger = logging.getLogger('KDDensity')

    def __init__(self, source, margin=1.0):
        if 'Position' not in source:
            raise ValueError("source needs a Position column")
        self.comm = source.comm
        BoxSize = np.ones(3) * np.asarray(source.attrs['BoxSize'],
                                          dtype='f8')
        self.attrs = dict(margin=margin, BoxSize=BoxSize)

        N = source.csize
        mean_sep = (np.prod(BoxSize) / N) ** (1.0 / 3)
        r = margin * mean_sep
        self.attrs['kernel_radius'] = r
        vol = 4.0 / 3 * np.pi * r ** 3

        from ..parallel.runtime import mesh_size
        nproc = mesh_size(self.comm)
        if nproc > 1 and r <= BoxSize[0] / nproc:
            pos = jnp.asarray(source['Position'])
            counts = _kdd_counts_dist(pos, BoxSize, r, self.comm,
                                      periodic=True)
            self.density = counts / vol
            return

        pos = as_numpy(source['Position'])
        from ..ops.gridhash import GridHash
        grid = GridHash(pos, BoxSize, r, periodic=True)
        r2 = r * r

        @jax.jit
        def neighbor_counts(p):
            ci = grid.cell_of(p)
            def body(total, j, valid, d, rr2):
                return total + jnp.where(valid & (rr2 <= r2), 1.0, 0.0)
            return grid.fold(p, ci, body, jnp.zeros(p.shape[0]))

        counts_per = neighbor_counts(jnp.asarray(pos))
        self.density = counts_per / vol
