"""KDDensity: a fast per-particle density proxy.

Reference: ``nbodykit/algorithms/kdtree.py:9`` — crude density from
nearest-neighbor distances (scipy cKDTree + domain ghosts there).
TPU redesign: neighbor *counts* within a kernel radius via the same
grid-hash sweep as FOF/pair counting, fully vectorized; the density
proxy is count / kernel volume.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..utils import as_numpy


class KDDensity(object):
    """Estimate a local density proxy for every object.

    Parameters
    ----------
    source : CatalogSource with Position and attrs['BoxSize']
    margin : float — kernel radius in units of the mean inter-particle
        separation (reference uses a margin-scaled proximity too)

    Attributes
    ----------
    density : (N,) density proxy (neighbors within the kernel / kernel
        volume), same normalization role as the reference's proxy.
    """

    logger = logging.getLogger('KDDensity')

    def __init__(self, source, margin=1.0):
        if 'Position' not in source:
            raise ValueError("source needs a Position column")
        self.comm = source.comm
        BoxSize = np.ones(3) * np.asarray(source.attrs['BoxSize'],
                                          dtype='f8')
        self.attrs = dict(margin=margin, BoxSize=BoxSize)

        pos = as_numpy(source['Position'])
        N = len(pos)
        mean_sep = (np.prod(BoxSize) / N) ** (1.0 / 3)
        r = margin * mean_sep
        self.attrs['kernel_radius'] = r

        from .pair_counters.core import _hash_secondary, neighbor_offsets
        order, flat_s, ncell, cellsize, K = _hash_secondary(
            pos, BoxSize, r)
        offs_list = neighbor_offsets(ncell)
        pos_s = jnp.asarray(pos[order])
        ncells_tot = int(np.prod(ncell))
        start = jnp.asarray(np.searchsorted(flat_s,
                                            np.arange(ncells_tot)))
        count = jnp.asarray(np.searchsorted(
            flat_s, np.arange(ncells_tot), side='right')) - start

        ncell_j = jnp.asarray(ncell, jnp.int32)
        cellsize_j = jnp.asarray(cellsize)
        boxj = jnp.asarray(BoxSize)
        offs = jnp.asarray(offs_list, dtype=jnp.int32)
        r2 = r * r

        @jax.jit
        def neighbor_counts(p):
            ci = jnp.clip((p / cellsize_j).astype(jnp.int32), 0,
                          ncell_j - 1)
            total = jnp.zeros(p.shape[0])
            for oi in range(len(offs_list)):
                nc = jnp.mod(ci + offs[oi], ncell_j)
                nflat = (nc[:, 0] * ncell_j[1] + nc[:, 1]) \
                    * ncell_j[2] + nc[:, 2]
                s = start[nflat]
                c = count[nflat]
                for slot in range(K):
                    j = s + slot
                    valid = slot < c
                    j = jnp.where(valid, j, 0)
                    d = p - pos_s[j]
                    d = d - jnp.round(d / boxj) * boxj
                    rr2 = jnp.sum(d * d, axis=-1)
                    total = total + jnp.where(valid & (rr2 <= r2),
                                              1.0, 0.0)
            return total

        counts_per = neighbor_counts(jnp.asarray(pos))
        vol = 4.0 / 3 * np.pi * r ** 3
        self.density = counts_per / vol
