from .tpcf import SimulationBox2PCF, SurveyData2PCF

__all__ = ['SimulationBox2PCF', 'SurveyData2PCF']
