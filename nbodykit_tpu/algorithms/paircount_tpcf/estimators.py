"""Correlation-function estimators from pair counts.

Reference: ``nbodykit/algorithms/paircount_tpcf/estimators.py`` —
AnalyticUniformRandoms (:54), LandySzalayEstimator (:142),
NaturalEstimator (:234), WedgeBinnedStatistic.to_poles (:5-53).
"""

import numpy as np

from ...binned_statistic import BinnedStatistic


class WedgeBinnedStatistic(BinnedStatistic):
    """A (r, mu) wedge dataset that can rotate into multipoles."""

    def to_poles(self, poles):
        """xi_ell(r) = (2 ell + 1) * sum_wedges xi(r, mu_c) P_ell(mu_c)
        dmu (reference estimators.py:5-53, trapezoidal in wedges)."""
        from numpy.polynomial.legendre import legval
        mu_edges = self.edges['mu']
        mu_c = 0.5 * (mu_edges[1:] + mu_edges[:-1])
        dmu = np.diff(mu_edges)
        xi = self['corr']
        data = {}
        for ell in poles:
            c = np.zeros(ell + 1)
            c[ell] = 1.0
            leg = legval(mu_c, c)
            data['corr_%d' % ell] = (2 * ell + 1) * np.nansum(
                xi * leg * dmu, axis=-1)
        data['r'] = self['r'].mean(axis=-1) if self['r'].ndim > 1 \
            else self['r']
        out = BinnedStatistic(['r'], [self.edges['r']], data)
        out.attrs.update(self.attrs)
        return out


def analytic_random_pairs(mode, edges, NR, BoxSize, Nmu=None,
                          pimax=None):
    """Expected (unweighted) pair counts of NR uniform points in a
    periodic box — the RR term without random catalogs (reference
    AnalyticUniformRandoms, estimators.py:54-141)."""
    V = np.prod(BoxSize)
    edges = np.asarray(edges, dtype='f8')
    if mode == '1d':
        vol = 4.0 / 3 * np.pi * np.diff(edges ** 3)
    elif mode == '2d':
        # uniform in mu in [0,1] counts both hemispheres
        muedges = np.linspace(0, 1, Nmu + 1)
        vol = (4.0 / 3 * np.pi * np.diff(edges ** 3)[:, None]
               * np.diff(muedges)[None, :])
    elif mode == 'projected':
        piedges = np.arange(0, int(pimax) + 1)
        vol = (np.pi * np.diff(edges ** 2)[:, None]
               * 2.0 * np.diff(piedges)[None, :])
    elif mode == 'angular':
        # spherical-cap ring area fraction (the reference's
        # AnalyticUniformRandoms mode='angular',
        # estimators.py:106-113). The exact cap area out to angular
        # radius theta is 2*pi*(1 - cos(theta)), so the ring between
        # consecutive theta edges (degrees) occupies the fraction
        # (cos(theta_lo) - cos(theta_hi)) / 2 of the full sphere —
        # exact at every opening angle (the reference's chord-based
        # expression is a small-angle approximation that turns
        # imaginary past 60 degrees).
        frac = -0.5 * np.diff(np.cos(np.deg2rad(edges)))
        return NR * (NR - 1) * frac
    else:
        raise ValueError("no analytic randoms for mode %r" % mode)
    return NR * (NR - 1) * vol / V


def natural_estimator(DD, mode, BoxSize, Nmu=None, pimax=None):
    """xi = DD / RR_analytic - 1 with analytic periodic-box randoms
    (reference NaturalEstimator)."""
    edges = DD.attrs['edges']
    total = DD.attrs['total_wnpairs']
    RRfrac = analytic_random_pairs(mode, edges, 2, BoxSize, Nmu=Nmu,
                                   pimax=pimax) / 2.0  # pair fraction
    fDD = DD['wnpairs'] / total
    with np.errstate(invalid='ignore', divide='ignore'):
        xi = fDD / RRfrac.reshape(fDD.shape) - 1.0
    return xi


def landy_szalay(DD, DR, RR, RD=None):
    """xi = (DD - DR - RD + RR) / RR with counts normalized by their
    total weighted pairs (reference LandySzalayEstimator,
    estimators.py:142)."""
    fDD = DD['wnpairs'] / DD.attrs['total_wnpairs']
    fDR = DR['wnpairs'] / DR.attrs['total_wnpairs']
    fRR = RR['wnpairs'] / RR.attrs['total_wnpairs']
    fRD = fDR if RD is None else RD['wnpairs'] / \
        RD.attrs['total_wnpairs']
    with np.errstate(invalid='ignore', divide='ignore'):
        xi = (fDD - fDR - fRD + fRR) / fRR
    return xi
