"""Two-point correlation functions from pair counts.

Reference: ``nbodykit/algorithms/paircount_tpcf/tpcf.py`` —
SimulationBox2PCF (:198) with analytic or catalog randoms,
SurveyData2PCF (:339) with Landy-Szalay, wp(rp) projection (:475).
"""

import logging

import numpy as np

from ..pair_counters.simbox import SimulationBoxPairCount
from ..pair_counters.mocksurvey import SurveyDataPairCount
from .estimators import (WedgeBinnedStatistic, natural_estimator,
                         landy_szalay)
from ...binned_statistic import BinnedStatistic


class BasePairCount2PCF(object):
    """Shared packaging: .corr / .D1D2 / .R1R2 etc. and wp."""

    def _package(self, xi, mode, edges, Nmu=None, pimax=None):
        data = {'corr': np.atleast_1d(xi)}
        if mode == '1d':
            dims, bes = ['r'], [edges]
            data['r'] = 0.5 * (edges[1:] + edges[:-1])
        elif mode == '2d':
            dims = ['r', 'mu']
            mue = np.linspace(0, 1, Nmu + 1)
            bes = [edges, mue]
            data['r'] = np.broadcast_to(
                0.5 * (edges[1:] + edges[:-1])[:, None],
                xi.shape).copy()
            data['mu'] = np.broadcast_to(
                0.5 * (mue[1:] + mue[:-1])[None, :], xi.shape).copy()
        elif mode == 'projected':
            dims = ['rp', 'pi']
            pie = np.arange(0, int(pimax) + 1)
            bes = [edges, pie]
            data['rp'] = np.broadcast_to(
                0.5 * (edges[1:] + edges[:-1])[:, None],
                xi.shape).copy()
        elif mode == 'angular':
            dims, bes = ['theta'], [edges]
            data['theta'] = 0.5 * (edges[1:] + edges[:-1])
        cls = WedgeBinnedStatistic if mode == '2d' else BinnedStatistic
        self.corr = cls(dims, bes, data)
        self.corr.attrs.update(self.attrs)

        if mode == 'projected':
            self.wp = self._compute_wp(xi, pie)

    def _compute_wp(self, xi, piedges):
        """wp(rp) = 2 * sum_pi xi(rp, pi) dpi (reference
        tpcf.py:475)."""
        dpi = np.diff(piedges)
        wp = 2.0 * np.nansum(xi * dpi[None, :], axis=-1)
        edges = self.attrs['edges']
        out = BinnedStatistic(
            ['rp'], [edges],
            {'corr': wp, 'rp': 0.5 * (edges[1:] + edges[:-1])})
        out.attrs.update(self.attrs)
        return out

    def save(self, output):
        import json
        from ...utils import JSONEncoder
        with open(output, 'w') as ff:
            json.dump(dict(corr=self.corr.__getstate__(),
                           attrs=self.attrs), ff, cls=JSONEncoder)


class SimulationBox2PCF(BasePairCount2PCF):
    """xi(r), xi(r,mu), xi(rp,pi)+wp, or w(theta) in a periodic box.

    With ``randoms1=None`` and periodic data, RR comes analytically
    (natural estimator); otherwise Landy-Szalay with the given randoms
    (reference tpcf.py:198).
    """

    logger = logging.getLogger('SimulationBox2PCF')

    def __init__(self, mode, data1, edges, Nmu=None, pimax=None,
                 data2=None, randoms1=None, randoms2=None,
                 periodic=True, BoxSize=None, los='z', weight='Weight',
                 show_progress=False):
        if BoxSize is None:
            BoxSize = data1.attrs['BoxSize']
        BoxSize = np.ones(3) * np.asarray(BoxSize, dtype='f8')
        self.attrs = dict(mode=mode, edges=np.asarray(edges, 'f8'),
                          Nmu=Nmu, pimax=pimax, periodic=periodic,
                          BoxSize=BoxSize, los=los)

        kw = dict(BoxSize=BoxSize, periodic=periodic, weight=weight,
                  los=los, Nmu=Nmu, pimax=pimax)
        self.D1D2 = SimulationBoxPairCount(mode, data1, edges,
                                           second=data2, **kw)

        if randoms1 is None:
            if not periodic and mode != 'angular':
                raise ValueError("need randoms for non-periodic data")
            xi = natural_estimator(self.D1D2.pairs, mode, BoxSize,
                                   Nmu=Nmu, pimax=pimax)
            self.R1R2 = None
        else:
            R1 = randoms1
            R2 = randoms2 if randoms2 is not None else randoms1
            self.D1R2 = SimulationBoxPairCount(mode, data1, edges,
                                               second=R2, **kw)
            self.D2R1 = self.D1R2 if data2 is None else \
                SimulationBoxPairCount(mode, data2 or data1, edges,
                                       second=R1, **kw)
            self.R1R2 = SimulationBoxPairCount(
                mode, R1, edges,
                second=None if randoms2 is None else R2, **kw)
            xi = landy_szalay(self.D1D2.pairs, self.D1R2.pairs,
                              self.R1R2.pairs, RD=self.D2R1.pairs)

        self._package(xi, mode, np.asarray(edges, 'f8'), Nmu=Nmu,
                      pimax=pimax)


class SurveyData2PCF(BasePairCount2PCF):
    """Landy-Szalay correlation of survey data + randoms (reference
    tpcf.py:339)."""

    logger = logging.getLogger('SurveyData2PCF')

    def __init__(self, mode, data, randoms, edges, cosmo=None,
                 Nmu=None, pimax=None, ra='RA', dec='DEC',
                 redshift='Redshift', weight='Weight',
                 show_progress=False):
        self.attrs = dict(mode=mode, edges=np.asarray(edges, 'f8'),
                          Nmu=Nmu, pimax=pimax)
        kw = dict(cosmo=cosmo, Nmu=Nmu, pimax=pimax, ra=ra, dec=dec,
                  redshift=redshift, weight=weight)
        self.D1D2 = SurveyDataPairCount(mode, data, edges, **kw)
        self.D1R2 = SurveyDataPairCount(mode, data, edges,
                                        second=randoms, **kw)
        self.R1R2 = SurveyDataPairCount(mode, randoms, edges, **kw)
        xi = landy_szalay(self.D1D2.pairs, self.D1R2.pairs,
                          self.R1R2.pairs)
        self._package(xi, mode, np.asarray(edges, 'f8'), Nmu=Nmu,
                      pimax=pimax)
