"""Isotropic 3-point correlation function multipoles.

Reference: ``nbodykit/algorithms/threeptcf.py:8`` — the Slepian &
Eisenstein (2015) O(N^2) algorithm: around every primary, accumulate
spherical-harmonic moments a_lm(r-bin) of its neighbors; then

    zeta_l(b1, b2) = sum_i w_i (4 pi / (2l+1)) sum_m
                         a_lm(i, b1) a_lm(i, b2)
                   = sum_i w_i sum_{j in b1, k in b2} w_j w_k
                         P_l(rhat_ij . rhat_ik)

(real-Ylm addition theorem). The reference builds its Ylm table with
sympy (YlmCache, :393); here the jnp real harmonics of
:func:`..convpower.fkp.get_real_Ylm` are reused, so the whole neighbor
sweep + moment accumulation + (b1, b2) outer product runs as one jitted
program (the outer product lands on the MXU).

With a device mesh active the sweep runs domain-decomposed (the
reference decomposes with ``smoothing=rmax`` ghosts through the pair-
counting machinery, threeptcf.py:6,60): particles route to x-slab
owners with both-side ghost copies within rmax, every device
accumulates a_lm moments for its *owned* primaries against its local
(owned + ghost) secondaries, and the per-ell zeta matrices are
psum-reduced — no device ever holds the full particle set.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp

from .convpower.fkp import get_real_Ylm
from ..binned_statistic import BinnedStatistic
from ..utils import as_numpy
from .. import transform


def _se_chunk_zeta(grid, w_s, ylms, nbins, r2edges):
    """The per-chunk Slepian–Eisenstein accumulation shared by the
    single-device and distributed drivers: a_lm(r-bin) moments of each
    primary's neighbors (via ``grid.fold``), then the per-ell
    (b1, b2) outer product zeta_l = (4pi/(2l+1)) sum_m a_lm a_lm^T.

    ``grid`` is a GridHash or DeviceGridHash whose sorted weights are
    ``w_s``; the returned callable maps (positions, weights, live-mask)
    chunks to a stacked (nell, nbins, nbins) zeta contribution.
    """
    nlm = sum(2 * ell + 1 for ell, _ in ylms)
    pvary = getattr(grid, 'pvary', lambda x: x)

    def chunk_zeta(args):
        p1c, w1c, live = args
        C = p1c.shape[0]
        ci = grid.cell_of(p1c)
        alm0 = pvary(jnp.zeros((C, nlm, nbins)))

        def body(alm, j, valid, d, r2):
            ok = valid & live & (r2 > 1e-20)
            rr = jnp.sqrt(jnp.where(r2 == 0, 1.0, r2))
            u = d / rr[:, None]
            dig = jnp.digitize(r2, r2edges) - 1
            inb = ok & (dig >= 0) & (dig < nbins)
            digc = jnp.clip(dig, 0, nbins - 1)
            wj = jnp.where(inb, w_s[j], 0.0)
            onehot = jax.nn.one_hot(digc, nbins) * wj[:, None]
            yvs = []
            for ell, Ys in ylms:
                for Y in Ys:
                    yvs.append(Y(u[:, 0], u[:, 1], u[:, 2]))
            yv = jnp.stack(yvs, axis=1)  # (C, nlm)
            return alm + yv[:, :, None] * onehot[:, None, :]

        alm = grid.fold(p1c, ci, body, alm0)
        outs = []
        ilm = 0
        for ell, Ys in ylms:
            nm = 2 * ell + 1
            a = alm[:, ilm:ilm + nm, :]  # (C, nm, nbins)
            z = jnp.einsum('i,imb,imc->bc', w1c, a, a)
            # reference normalization: corr_ell such that
            # corr_ell * (4pi)^2 / (2ell+1) = sum_i w_i w_j w_k
            # P_ell(rhat_ij . rhat_ik)  (the Eisenstein C++ output
            # convention the reference's golden test encodes;
            # test_threeptcf.py:54)
            outs.append(z / (4 * np.pi))
            ilm += nm
        return jnp.stack(outs)

    return chunk_zeta


class Base3PCF(object):
    """Shared SE accumulation (reference threeptcf.py:35-190)."""

    def _run(self, pos, w, edges, poles, BoxSize=None, periodic=True):
        edges = np.asarray(edges, dtype='f8')
        nbins = len(edges) - 1
        rmax = edges[-1]
        N = len(pos)

        if BoxSize is None:
            lo = pos.min(axis=0)
            hi = pos.max(axis=0)
            box = (hi - lo) * 1.001 + 1e-3
            origin = lo
            periodic = False
        else:
            box = np.ones(3) * np.asarray(BoxSize, dtype='f8')
            origin = np.zeros(3)

        from ..ops.gridhash import GridHash
        grid = GridHash(pos - origin, box, rmax, periodic=periodic)
        w_s = jnp.asarray(w[grid.order])
        r2edges = jnp.asarray(edges ** 2)

        ells = sorted(poles)
        ylms = [(ell, [get_real_Ylm(ell, m)
                       for m in range(-ell, ell + 1)]) for ell in ells]
        chunk_zeta = _se_chunk_zeta(grid, w_s, ylms, nbins, r2edges)

        chunk = 2048
        nchunks = max(1, (N + chunk - 1) // chunk)
        npad = nchunks * chunk
        p1 = np.concatenate([pos - origin, np.zeros((npad - N, 3))])
        w1 = np.concatenate([w, np.zeros(npad - N)])
        live = np.concatenate([np.ones(N, bool),
                               np.zeros(npad - N, bool)])
        res = jax.lax.map(chunk_zeta,
                          (jnp.asarray(p1).reshape(nchunks, chunk, 3),
                           jnp.asarray(w1).reshape(nchunks, chunk),
                           jnp.asarray(live).reshape(nchunks, chunk)))
        zetas = np.array(res.sum(axis=0))  # (nell, nbins, nbins)
        return self._package(zetas, edges, sorted(poles))

    def _run_dist(self, pos, w, edges, poles, mesh, BoxSize=None,
                  periodic=True):
        """Device-mesh SE sweep: sharded positions in, psum'd zetas
        out. Mirrors :meth:`_run` slab-decomposed (ghosts='both')."""
        from jax.sharding import PartitionSpec as P
        from ..parallel.runtime import AXIS, shard_leading
        from ..parallel.domain import slab_route
        from ..ops.devicehash import DeviceGridHash

        edges = np.asarray(edges, dtype='f8')
        nbins = len(edges) - 1
        rmax = float(edges[-1])
        N = int(pos.shape[0])

        if BoxSize is None:
            lo = np.asarray(jnp.min(pos, axis=0))
            hi = np.asarray(jnp.max(pos, axis=0))
            box = (hi - lo) * 1.001 + 1e-3
            origin = jnp.asarray(lo, pos.dtype)
            periodic = False
        else:
            box = np.ones(3) * np.asarray(BoxSize, dtype='f8')
            origin = jnp.zeros(3, pos.dtype)

        pos = pos - origin
        route, f, live = slab_route(pos, box, rmax, mesh,
                                    ghosts='both', periodic=periodic,
                                    balance=True)
        own = jnp.concatenate(
            [jnp.ones(N, bool)] + [jnp.zeros(N, bool)] * (f - 1))
        w = jnp.asarray(w)
        (pos_r, w_r, own_r, live_r), ok, _ = route.exchange(
            [jnp.concatenate([pos] * f), jnp.concatenate([w] * f),
             own, live])
        valid = ok & live_r

        ells = sorted(poles)
        ylms = [(ell, [get_real_Ylm(ell, m)
                       for m in range(-ell, ell + 1)]) for ell in ells]
        r2edges = jnp.asarray(edges ** 2)
        chunk = 2048

        def local(p, wv, v, own_l):
            grid = DeviceGridHash(p, box, rmax, valid=v,
                                  periodic=periodic, axis_name=AXIS)
            w_s = wv[grid.order]
            S = p.shape[0]
            nchunks = max(1, (S + chunk - 1) // chunk)
            npad = nchunks * chunk
            pad = npad - S
            p1 = jnp.concatenate([p, jnp.zeros((pad, 3), p.dtype)])
            w1 = jnp.concatenate([wv, jnp.zeros(pad, wv.dtype)])
            prim = jnp.concatenate([own_l & v, jnp.zeros(pad, bool)])
            chunk_zeta = _se_chunk_zeta(grid, w_s, ylms, nbins,
                                        r2edges)

            res = jax.lax.map(
                chunk_zeta,
                (p1.reshape(nchunks, chunk, 3),
                 w1.reshape(nchunks, chunk),
                 prim.reshape(nchunks, chunk)))
            return jax.lax.psum(res.sum(axis=0), AXIS)

        zetas = np.array(jax.jit(jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(AXIS, None), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=P()))(pos_r, w_r, valid, own_r))
        return self._package(zetas, edges, ells)

    def _package(self, zetas, edges, ells):
        nbins = len(edges) - 1
        data = {}
        centers = 0.5 * (edges[1:] + edges[:-1])
        data['r1'] = np.broadcast_to(centers[:, None],
                                     (nbins, nbins)).copy()
        data['r2'] = np.broadcast_to(centers[None, :],
                                     (nbins, nbins)).copy()
        for i, ell in enumerate(ells):
            data['corr_%d' % ell] = zetas[i]
        poles_ds = BinnedStatistic(['r1', 'r2'], [edges, edges], data)
        poles_ds.attrs.update(self.attrs)
        return poles_ds

    def save(self, output):
        import json
        from ..utils import JSONEncoder
        with open(output, 'w') as ff:
            json.dump(dict(poles=self.poles.__getstate__(),
                           attrs=self.attrs), ff, cls=JSONEncoder)


class SimulationBox3PCF(Base3PCF):
    """zeta_l(r1, r2) in a periodic box (reference threeptcf.py:193)."""

    logger = logging.getLogger('SimulationBox3PCF')

    def __init__(self, source, poles, edges, BoxSize=None,
                 periodic=True, weight='Weight', position='Position'):
        self.comm = source.comm
        if BoxSize is None:
            BoxSize = source.attrs['BoxSize']
        self.attrs = dict(poles=list(poles),
                          edges=np.asarray(edges, 'f8'),
                          BoxSize=np.ones(3) * np.asarray(BoxSize),
                          periodic=periodic)
        from ..parallel.runtime import mesh_size
        nproc = mesh_size(self.comm)
        box = self.attrs['BoxSize']
        if nproc > 1 and np.max(edges) <= box[0] / nproc:
            pos = jnp.asarray(source[position])
            w = jnp.asarray(source[weight]) if weight in source else \
                jnp.ones(pos.shape[0])
            self.poles = self._run_dist(pos, w, edges, poles,
                                        self.comm, BoxSize=box,
                                        periodic=periodic)
            return
        pos = as_numpy(source[position])
        w = as_numpy(source[weight]) if weight in source else \
            np.ones(len(pos))
        self.poles = self._run(pos, w, edges, poles,
                               BoxSize=self.attrs['BoxSize'],
                               periodic=periodic)


class SurveyData3PCF(Base3PCF):
    """zeta_l(r1, r2) of survey (sky) data (reference
    threeptcf.py:290)."""

    logger = logging.getLogger('SurveyData3PCF')

    def __init__(self, source, poles, edges, cosmo, ra='RA', dec='DEC',
                 redshift='Redshift', weight='Weight'):
        self.comm = source.comm
        self.attrs = dict(poles=list(poles),
                          edges=np.asarray(edges, 'f8'))
        from ..parallel.runtime import mesh_size
        nproc = mesh_size(self.comm)
        posj = jnp.asarray(transform.SkyToCartesian(
            source[ra], source[dec], source[redshift], cosmo))
        if nproc > 1:
            span = np.asarray(jnp.max(posj, axis=0)
                              - jnp.min(posj, axis=0)) * 1.001 + 1e-3
            if np.max(edges) <= span[0] / nproc:
                w = jnp.asarray(source[weight]) if weight in source \
                    else jnp.ones(posj.shape[0])
                self.poles = self._run_dist(
                    posj, w, edges, poles, self.comm, BoxSize=None,
                    periodic=False)
                return
        pos = as_numpy(posj)
        w = as_numpy(source[weight]) if weight in source else \
            np.ones(len(pos))
        self.poles = self._run(pos, w, edges, poles, BoxSize=None,
                               periodic=False)


class YlmCache(object):
    """Complex spherical harmonics :math:`Y_{\\ell m}` up to a maximum
    :math:`\\ell`, evaluated on Cartesian unit vectors.

    API-compatible with the reference's sympy-backed cache
    (reference threeptcf.py:393-505): ``YlmCache(ells)(xpyhat, zhat)``
    — ``xpyhat`` the complex :math:`\\hat x + i \\hat y` — returns
    ``{(l, m): complex array}`` for ``m`` in ``0..l``. Here each
    harmonic is assembled from the closed-form real harmonics of
    :func:`..convpower.fkp.get_real_Ylm` via

    .. math:: Y_\\ell^m = \\frac{1}{\\sqrt 2}
              (Y_{\\ell m}^{\\rm real} + i\\, Y_{\\ell,-m}^{\\rm real})

    for :math:`m > 0` (and :math:`Y_\\ell^0 = Y_{\\ell 0}^{\\rm real}`),
    so no symbolic algebra or code generation is needed.
    """

    def __init__(self, ells, comm=None):
        self.ells = np.asarray(ells).astype(int)
        self.max_ell = int(self.ells.max())
        self.ell_to_iell = np.empty(self.max_ell + 1, dtype=int)
        for iell, ell in enumerate(self.ells):
            self.ell_to_iell[ell] = iell
        self._fns = {}
        for ell in self.ells:
            for m in range(0, ell + 1):
                fp = get_real_Ylm(ell, m)
                if m == 0:
                    self._fns[(ell, m)] = (fp, None)
                else:
                    self._fns[(ell, m)] = (fp, get_real_Ylm(ell, -m))

    def __call__(self, xpyhat, zhat):
        import math
        xhat, yhat = np.real(xpyhat), np.imag(xpyhat)
        toret = {}
        for (ell, m), (fp, fm) in self._fns.items():
            if fm is None:
                toret[(ell, m)] = fp(xhat, yhat, zhat)
            else:
                # the Condon-Shortley phase already lives in the real
                # harmonics' Legendre recurrence, so no extra (-1)^m
                s = 1.0 / math.sqrt(2.0)
                toret[(ell, m)] = s * (fp(xhat, yhat, zhat)
                                       + 1j * fm(xhat, yhat, zhat))
        return toret
