"""CylindricalGroups: cylinder-based group finder.

Reference: ``nbodykit/algorithms/cgm.py:12`` — the Okumura et al. 2017
cylindrical grouping method: objects are ranked (e.g. by mass); in rank
order, an object becomes a *central* if no higher-ranked central lies
within a cylinder of radius ``rperp`` and half-height ``rpar`` around
it (along the line of sight), else it is a *satellite* of the closest
such central.

Implementation: candidate neighbors come from the grid-hash pair
machinery; the rank-ordered sweep is a host loop (greedy by
construction, like the reference's sequential pass).
"""

import logging

import numpy as np

from ..source.catalog.array import ArrayCatalog
from ..utils import as_numpy


class CylindricalGroups(object):
    """Find cylindrical groups.

    Parameters (reference cgm.py:58): source, rankby (column name(s);
    descending priority), rperp, rpar, flat_sky_los (unit vector; None
    uses the z axis), periodic.

    Results in :attr:`groups` — ArrayCatalog with ``cgm_type``
    (0=central, 1=satellite, 2=isolated central), ``cgm_haloid`` (the
    central's index, for satellites), ``num_cgm_sats`` (for centrals).
    """

    logger = logging.getLogger('CylindricalGroups')

    def __init__(self, source, rankby, rperp, rpar, flat_sky_los=None,
                 periodic=True, BoxSize=None):
        if rankby is None:
            rankby = []
        if isinstance(rankby, str):
            rankby = [rankby]
        for col in rankby:
            if col not in source:
                raise ValueError("rankby column %r missing" % col)
        self.comm = source.comm
        if BoxSize is None:
            BoxSize = source.attrs.get('BoxSize', None)
        if periodic and BoxSize is None:
            raise ValueError("periodic grouping requires a BoxSize")
        if flat_sky_los is None:
            flat_sky_los = [0, 0, 1]
        flat_sky_los = np.asarray(flat_sky_los, dtype='f8')
        self.attrs = dict(rperp=rperp, rpar=rpar, periodic=periodic,
                          flat_sky_los=flat_sky_los, rankby=rankby)
        if BoxSize is not None:
            self.attrs['BoxSize'] = np.ones(3) * np.asarray(BoxSize)

        pos = as_numpy(source['Position'])
        N = len(pos)

        # descending rank order
        if rankby:
            keys = tuple(as_numpy(source[c]) for c in
                         reversed(rankby))
            order = np.lexsort(keys)[::-1]
        else:
            order = np.arange(N)
        rank_of = np.empty(N, dtype='i8')
        rank_of[order] = np.arange(N)

        box = self.attrs.get('BoxSize', None)
        rmax = np.sqrt(rperp ** 2 + rpar ** 2)

        # candidate pairs from the grid hash (host side)
        pairs = self._candidate_pairs(pos, box, rmax, periodic)

        los = flat_sky_los
        cgm_type = np.full(N, 2, dtype='i4')     # default isolated
        cgm_haloid = np.full(N, -1, dtype='i8')
        nsat = np.zeros(N, dtype='i8')

        # neighbor lists restricted to the cylinder
        nbr = [[] for _ in range(N)]
        for i, j in pairs:
            d = pos[i] - pos[j]
            if periodic:
                d = d - np.round(d / box) * box
            dpar = abs(np.dot(d, los))
            dperp2 = (d ** 2).sum() - dpar ** 2
            if dpar <= rpar and dperp2 <= rperp ** 2:
                nbr[i].append(j)
                nbr[j].append(i)

        # greedy sweep in rank order
        for i in order:
            if cgm_type[i] != 2 and cgm_type[i] != 0:
                continue
            # find higher-ranked centrals in the cylinder
            best = -1
            bestr = np.inf
            for j in nbr[i]:
                if rank_of[j] < rank_of[i] and cgm_type[j] in (0, 2):
                    d = pos[i] - pos[j]
                    if periodic:
                        d = d - np.round(d / box) * box
                    r2 = (d ** 2).sum()
                    if r2 < bestr:
                        bestr = r2
                        best = j
            if best >= 0:
                cgm_type[i] = 1
                cgm_haloid[i] = best
                if cgm_type[best] == 2:
                    cgm_type[best] = 0
                nsat[best] += 1
            # else stays central candidate (isolated unless it gains
            # satellites later)

        cgm_type[(cgm_type == 2) & (nsat > 0)] = 0

        self.groups = ArrayCatalog(
            {'cgm_type': cgm_type, 'cgm_haloid': cgm_haloid,
             'num_cgm_sats': nsat}, comm=self.comm)
        self.groups.attrs.update(self.attrs)

    @staticmethod
    def _candidate_pairs(pos, box, rmax, periodic):
        """Unique candidate pairs within rmax via cell hashing."""
        if box is None:
            lo = pos.min(axis=0)
            span = pos.max(axis=0) - lo + 1e-3
            work = span
            p = pos - lo
        else:
            work = np.asarray(box, dtype='f8')
            p = pos
        ncell = np.maximum(np.floor(work / rmax), 1).astype('i8')
        ncell = np.minimum(ncell, 64)
        cellsize = work / ncell
        ci = np.clip((p / cellsize).astype('i8'), 0, ncell - 1)
        flat = (ci[:, 0] * ncell[1] + ci[:, 1]) * ncell[2] + ci[:, 2]
        from collections import defaultdict
        cells = defaultdict(list)
        for idx, f in enumerate(flat):
            cells[int(f)].append(idx)

        from ..ops.gridhash import neighbor_offsets
        offs = neighbor_offsets(ncell, periodic=periodic)
        pairs = set()
        for f, members in cells.items():
            c0 = np.array([f // (ncell[1] * ncell[2]),
                           (f // ncell[2]) % ncell[1], f % ncell[2]])
            for off in offs:
                nc = c0 + off
                if periodic:
                    nc = nc % ncell
                elif np.any(nc < 0) or np.any(nc >= ncell):
                    continue
                nf = int((nc[0] * ncell[1] + nc[1]) * ncell[2] + nc[2])
                for i in members:
                    for j in cells.get(nf, ()):
                        if i < j:
                            pairs.add((i, j))
        return pairs
