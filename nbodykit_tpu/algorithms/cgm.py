"""CylindricalGroups: cylinder-based group finder.

Reference: ``nbodykit/algorithms/cgm.py:12`` — the Okumura et al. 2017
cylindrical grouping method: objects are ranked (e.g. by mass); in rank
order, an object becomes a *central* if no higher-ranked central lies
within a cylinder of radius ``rperp`` and half-height ``rpar`` around
it (along the line of sight), else it is a *satellite* of the
highest-priority such central (the reference sorts candidate pairs by
rank and keeps the first, cgm.py:150+).

TPU redesign: the reference resolves the rank order with a sequential
sweep over mpsort-sorted chunks (cgm.py:150+). The greedy recursion is
a fixpoint on the rank DAG — ``satellite(i) iff exists j in
cylinder(i) with rank(j) < rank(i) and not satellite(j)`` — so Jacobi
iteration of a vectorized cylinder sweep (grid-hash fold, one jitted
program per round) converges to the identical classification in
depth-of-the-DAG rounds. With a device mesh active the same rounds run
domain-decomposed: particles route to x-slab owners with both-side
ghost copies within sqrt(rperp^2+rpar^2), each round re-ships the
central flags along the frozen exchange plan, and per-owner verdicts
scatter back to the global table — no device ever holds the full
catalog (the role mpsort + chunked kdcount play in the reference).
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..source.catalog.array import ArrayCatalog
from ..utils import as_numpy


def _cylinder_sweep(grid, rank_s, central_s, los, rperp, rpar):
    """One Jacobi round on sorted slots: per query, the
    highest-priority (smallest rank) higher-ranked current-central
    within the cylinder (slot index, or -1) — the reference assigns
    satellites to the first central in rank order, not the nearest
    (cgm.py sorts pairs by rank and takes the head)."""
    ci = grid.cell_of(grid.pos_s)
    # rperp/rpar are static host config closed over by the jitted
    # sweep lambda, never traced values — audited, safe to coerce
    rp2 = jnp.asarray(float(rperp) ** 2, grid.pos_s.dtype)  # nbkl: disable=NBK401
    rpar_j = jnp.asarray(float(rpar), grid.pos_s.dtype)  # nbkl: disable=NBK401
    los_j = jnp.asarray(los, grid.pos_s.dtype)
    n = grid.pos_s.shape[0]

    def body(carry, j, valid, d, r2):
        bestrank, bestj = carry
        dpar = jnp.abs(d @ los_j)
        dperp2 = jnp.maximum(r2 - dpar * dpar, 0.0)
        ok = (valid & central_s[j] & (rank_s[j] < rank_s)
              & (dpar <= rpar_j) & (dperp2 <= rp2))
        better = ok & (rank_s[j] < bestrank)
        return (jnp.where(better, rank_s[j], bestrank),
                jnp.where(better, j, bestj))

    init = (jnp.full(n, jnp.iinfo(jnp.int32).max, jnp.int32),
            jnp.full(n, -1, jnp.int32))
    _, bestj = grid.fold(grid.pos_s, ci, body, init)
    return bestj


def _cgm_classify(pos, rank, box, rperp, rpar, los, periodic, mesh):
    """(satellite mask, haloid) in original order; haloid = -1 for
    non-satellites. ``rank``: i4, 0 = highest priority."""
    from ..ops.devicehash import DeviceGridHash
    from ..parallel.runtime import AXIS, mesh_size, shard_leading
    from ..parallel.domain import slab_route, scatter_reduce_by_index
    from jax.sharding import PartitionSpec as P

    rmax = float(np.sqrt(rperp ** 2 + rpar ** 2))
    if box is None:
        lo = np.asarray(jnp.min(pos, axis=0))
        work = np.asarray(jnp.max(pos, axis=0)) - lo + 1e-3
        pos = pos - jnp.asarray(lo, pos.dtype)
        periodic = False
    else:
        work = np.ones(3) * np.asarray(box, dtype='f8')

    nproc = mesh_size(mesh)
    N = int(pos.shape[0])

    if nproc == 1 or rmax > work[0] / nproc:
        grid = DeviceGridHash(jnp.asarray(pos), work, rmax,
                              periodic=periodic)
        rank_s = jnp.asarray(rank)[grid.order]

        # constructed once per classify call, then reused across every
        # Jacobi round of the while loop below; the closure is
        # grid-data-dependent so it cannot be hoisted to module scope
        sweep = jax.jit(lambda c: _cylinder_sweep(  # nbkl: disable=NBK202
            grid, rank_s, c, los, rperp, rpar))
        central = jnp.ones(N, bool)
        while True:
            bestj = sweep(central)
            central_new = bestj < 0
            if bool(jnp.all(central_new == central)):
                break
            central = central_new
        haloid_s = jnp.where(bestj >= 0,
                             grid.order.astype(jnp.int32)[
                                 jnp.maximum(bestj, 0)], -1)
        sat = jnp.zeros(N, bool).at[grid.order].set(bestj >= 0)
        haloid = jnp.full(N, -1, jnp.int32).at[grid.order].set(haloid_s)
        return np.asarray(sat), np.asarray(haloid)

    # distributed: slab owners + both-side ghosts; re-ship central
    # flags along the frozen plan each round
    route, f, live = slab_route(pos, work, rmax, mesh, ghosts='both',
                                periodic=periodic, balance=True)
    gid = shard_leading(mesh, jnp.arange(N, dtype=jnp.int32))
    own = jnp.concatenate(
        [jnp.ones(N, bool)] + [jnp.zeros(N, bool)] * (f - 1))
    rank_j = jnp.asarray(rank, jnp.int32)
    (pos_r, gid_r, rank_r, own_r, live_r), ok, _ = route.exchange(
        [jnp.concatenate([pos] * f),
         jnp.concatenate([gid] * f),
         jnp.concatenate([rank_j] * f), own, live])
    valid = ok & live_r

    def round_local(p, v, rank_l, central_l, gid_l, own_l):
        grid = DeviceGridHash(p, work, rmax, valid=v,
                              periodic=periodic, axis_name=AXIS)
        rank_s = rank_l[grid.order]
        central_s = central_l[grid.order] & grid.valid_s
        bestj = _cylinder_sweep(grid, rank_s, central_s, los,
                                rperp, rpar)
        gid_s = gid_l[grid.order]
        haloid_s = jnp.where(bestj >= 0,
                             gid_s[jnp.maximum(bestj, 0)], -1)
        S = p.shape[0]
        sat_l = jnp.zeros(S, bool).at[grid.order].set(bestj >= 0)
        haloid_out = jnp.full(S, -1, jnp.int32).at[grid.order].set(
            haloid_s)
        return sat_l, haloid_out

    # one construction per classify call, reused across the rank-round
    # while loop; mesh/shape-dependent closure — cannot hoist
    round_fn = jax.jit(jax.shard_map(  # nbkl: disable=NBK202
        round_local, mesh=mesh,
        in_specs=(P(AXIS, None),) + (P(AXIS),) * 5,
        out_specs=(P(AXIS), P(AXIS))))

    central = jnp.ones(N, bool)
    own_live = own_r & valid
    while True:
        central_f = jnp.concatenate([central] * f)
        (central_r,), _, _ = route.exchange([central_f])
        sat_r, haloid_r = round_fn(pos_r, valid, rank_r,
                                   central_r & valid, gid_r, own_r)
        sat_g = scatter_reduce_by_index(
            gid_r, sat_r.astype(jnp.int32), N, mesh, op='max',
            valid=own_live)[:N] > 0
        central_new = ~sat_g
        if bool(jnp.all(central_new == central)):
            haloid = scatter_reduce_by_index(
                gid_r, haloid_r, N, mesh, op='max',
                valid=own_live)[:N]
            haloid = jnp.where(sat_g, haloid, -1)
            return np.asarray(sat_g), np.asarray(haloid)
        central = central_new


class CylindricalGroups(object):
    """Find cylindrical groups.

    Parameters (reference cgm.py:58): source, rankby (column name(s);
    descending priority), rperp, rpar, flat_sky_los (unit vector; None
    uses the z axis), periodic.

    Results in :attr:`groups` — ArrayCatalog with ``cgm_type``
    (0=central, 1=satellite; isolated centrals are type 0 with
    ``num_cgm_sats == 0``, matching the reference's output schema,
    cgm.py:133-134,187-188), ``cgm_haloid`` (the central's index, for
    satellites), ``num_cgm_sats`` (for centrals).
    """

    logger = logging.getLogger('CylindricalGroups')

    def __init__(self, source, rankby, rperp, rpar, flat_sky_los=None,
                 periodic=True, BoxSize=None):
        if rankby is None:
            rankby = []
        if isinstance(rankby, str):
            rankby = [rankby]
        for col in rankby:
            if col not in source:
                raise ValueError("rankby column %r missing" % col)
        self.comm = source.comm
        if BoxSize is None:
            BoxSize = source.attrs.get('BoxSize', None)
        if periodic and BoxSize is None:
            raise ValueError("periodic grouping requires a BoxSize")
        if flat_sky_los is None:
            flat_sky_los = [0, 0, 1]
        flat_sky_los = np.asarray(flat_sky_los, dtype='f8')
        self.attrs = dict(rperp=rperp, rpar=rpar, periodic=periodic,
                          flat_sky_los=flat_sky_los, rankby=rankby)
        box = None
        if BoxSize is not None:
            box = np.ones(3) * np.asarray(BoxSize)
            self.attrs['BoxSize'] = box

        N = source.csize
        # descending rank order (host: the keys are small 1-D columns;
        # the reference sorts them globally with mpsort, cgm.py:150)
        if rankby:
            keys = tuple(as_numpy(source[c]) for c in reversed(rankby))
            order = np.lexsort(keys)[::-1]
        else:
            order = np.arange(N)
        rank_of = np.empty(N, dtype='i4')
        rank_of[order] = np.arange(N, dtype='i4')

        pos = jnp.asarray(source['Position'])
        sat, haloid = _cgm_classify(pos, rank_of, box, rperp, rpar,
                                    flat_sky_los,
                                    self.attrs['periodic'], self.comm)

        nsat = np.bincount(haloid[sat], minlength=N).astype('i8')
        cgm_type = np.zeros(N, dtype='i4')
        cgm_type[sat] = 1
        cgm_haloid = np.where(sat, haloid, -1).astype('i8')

        self.groups = ArrayCatalog(
            {'cgm_type': cgm_type, 'cgm_haloid': cgm_haloid,
             'num_cgm_sats': nsat}, comm=self.comm)
        self.groups.attrs.update(self.attrs)
