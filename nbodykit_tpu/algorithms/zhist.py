"""RedshiftHistogram: weighted n(z) of a catalog.

Reference: ``nbodykit/algorithms/zhist.py:9`` — histogram of a redshift
column with automatic Scott's-rule binning, normalized to the comoving
number density n(z) using a fiducial cosmology.
"""

import logging

import numpy as np

from ..binned_statistic import BinnedStatistic
from ..utils import as_numpy


def scotts_bin_width(data):
    """Scott's rule bin width: 3.5 sigma / N^(1/3)."""
    data = np.asarray(data)
    sigma = data.std()
    n = len(data)
    if sigma == 0 or n == 0:
        return 0.1
    return 3.5 * sigma / n ** (1.0 / 3)


class RedshiftHistogram(object):
    """n(z) from a catalog.

    Parameters (reference zhist.py): source, fsky (sky fraction the
    catalog covers), cosmo (for comoving volumes), bins (int, edges, or
    None for Scott's rule), redshift/weight column names.

    Attributes
    ----------
    bin_edges, bin_centers : the z binning
    dV : comoving volume per bin, (Mpc/h)^3
    nbar : weighted number density per bin
    """

    logger = logging.getLogger('RedshiftHistogram')

    def __init__(self, source, fsky, cosmo, bins=None, redshift='Redshift',
                 weight=None):
        self.source = source
        self.comm = source.comm
        self.attrs = dict(fsky=fsky, redshift=redshift, weight=weight)

        z = as_numpy(source[redshift])
        w = as_numpy(source[weight]) if weight is not None else \
            np.ones(len(z))

        if bins is None:
            dz = scotts_bin_width(z)
            bins = np.arange(z.min(), z.max() + dz, dz)
        elif np.isscalar(bins):
            bins = np.linspace(z.min(), z.max(), int(bins) + 1)
        bins = np.asarray(bins, dtype='f8')

        counts, _ = np.histogram(z, bins=bins, weights=w)

        # comoving volume of each shell, scaled by fsky
        r = cosmo.comoving_distance(bins)
        dV = fsky * 4.0 / 3 * np.pi * np.diff(r ** 3)

        self.bin_edges = bins
        self.bin_centers = 0.5 * (bins[1:] + bins[:-1])
        self.dV = dV
        self.nbar = counts / dV

        data = {'z': self.bin_centers, 'nbar': self.nbar,
                'counts': counts, 'dV': dV}
        self.hist = BinnedStatistic(['z'], [bins], data,
                                    fields_to_sum=['counts', 'dV'])
        self.hist.attrs.update(self.attrs)

    def interpolate(self, z):
        """n(z) interpolated at arbitrary redshifts (for building NZ
        columns)."""
        return np.interp(np.asarray(z), self.bin_centers, self.nbar,
                         left=0.0, right=0.0)

    def __getstate__(self):
        return dict(bin_edges=self.bin_edges, nbar=self.nbar,
                    dV=self.dV, attrs=self.attrs)
