"""Algorithms (SURVEY.md §2 L4): FFT-based spectra estimators, group
finders, pair counting, and histograms."""

from .fftpower import FFTPower, ProjectedFFTPower, FFTBase, project_to_basis
from .fftcorr import FFTCorr
from .convpower import ConvolvedFFTPower, FKPCatalog, FKPWeightFromNbar
from .fftrecon import FFTRecon
from .bispectrum import Bispectrum

__all__ = ['FFTPower', 'ProjectedFFTPower', 'FFTBase', 'FFTCorr',
           'ConvolvedFFTPower', 'FKPCatalog', 'FKPWeightFromNbar', 'FFTRecon',
           'Bispectrum', 'project_to_basis']
