"""Algorithms (SURVEY.md §2 L4): FFT-based spectra estimators, group
finders, pair counting, and histograms."""

from .fftpower import FFTPower, ProjectedFFTPower, FFTBase, project_to_basis
from .fftcorr import FFTCorr

__all__ = ['FFTPower', 'ProjectedFFTPower', 'FFTBase', 'FFTCorr',
           'project_to_basis']
