from .fkp import ConvolvedFFTPower, get_real_Ylm
from .catalog import FKPCatalog, FKPWeightFromNbar
from .catalogmesh import FKPCatalogMesh

__all__ = ['ConvolvedFFTPower', 'FKPCatalog', 'FKPCatalogMesh',
           'FKPWeightFromNbar', 'get_real_Ylm']
