"""ConvolvedFFTPower: survey-geometry power-spectrum multipoles.

Reference: ``nbodykit/algorithms/convpower/fkp.py:75`` — the Hand et
al. 2017 estimator (building on Bianchi 2015 / Scoccimarro 2015): via
the spherical-harmonic addition theorem, each multipole needs only
2l+1 FFTs of Ylm-weighted density fields.

TPU redesign: the reference generates real Ylm with sympy->numexpr
codegen (:12-73); here they are closed-form jnp polynomials via the
associated-Legendre recurrence (:func:`get_real_Ylm`), so the whole
Ylm-weight -> FFT -> Ylm-weight -> accumulate loop stays inside jitted
XLA programs over the sharded mesh.

Even multipoles ride the hermitian (r2c) fast path; requesting any odd
multipole switches to the full complex (c2c) spectrum automatically —
the analog of the reference's dtype='c16' mesh — since the hermitian
shortcut is only exact for even ell under a varying line of sight.
"""

import logging
import time

import numpy as np
import jax
import jax.numpy as jnp

from ...binned_statistic import BinnedStatistic
from ...utils import JSONEncoder, JSONDecoder, working_dtype
from ..fftpower import project_to_basis, _find_unique_edges
from ...base.mesh import Field
from .catalogmesh import FKPCatalogMesh
from .catalog import FKPCatalog
from ...ops.window import compensation_transfer


def get_real_Ylm(l, m):
    """A jnp-evaluable real spherical harmonic Y_lm(x, y, z) on unit
    vectors (reference: sympy-generated at convpower/fkp.py:12-73).

    Uses P_l^m(z) = (sin theta)^m W_lm(z) with the polynomial recurrence
      W_mm = (-1)^m (2m-1)!!,  W_{m+1,m} = z (2m+1) W_mm,
      W_lm = ((2l-1) z W_{l-1,m} - (l+m-1) W_{l-2,m}) / (l - m),
    and (sin theta)^m cos/sin(m phi) = Re/Im[(x + i y)^m] — polynomial
    in (x, y, z), hence pole-safe.
    """
    m_abs = abs(m)

    # normalization sqrt((2l+1)/(4pi) (l-m)!/(l+m)!)
    from math import factorial, sqrt, pi
    norm = sqrt((2 * l + 1) / (4 * pi)
                * factorial(l - m_abs) / factorial(l + m_abs))
    if m != 0:
        norm *= sqrt(2.0)

    def Ylm(x, y, z):
        # W_lm(z) by recurrence
        Wmm = 1.0
        for i in range(m_abs):
            Wmm = -Wmm * (2 * i + 1)
        W_prev = jnp.full_like(z, Wmm)
        if l == m_abs:
            W = W_prev
        else:
            W_cur = z * (2 * m_abs + 1) * Wmm
            for ll in range(m_abs + 2, l + 1):
                W_next = ((2 * ll - 1) * z * W_cur
                          - (ll + m_abs - 1) * W_prev) / (ll - m_abs)
                W_prev, W_cur = W_cur, W_next
            W = W_cur if l > m_abs else W_prev
        # azimuthal factor via complex powers
        if m_abs == 0:
            azim = 1.0
        else:
            re, im = x, y
            for _ in range(m_abs - 1):
                re, im = re * x - im * y, re * y + im * x
            azim = re if m >= 0 else im
        return norm * W * azim

    Ylm.l = l
    Ylm.m = m
    return Ylm


class ConvolvedFFTPower(object):
    """Power-spectrum multipoles of an FKP-weighted survey catalog.

    Parameters (reference convpower/fkp.py:134):
    first : FKPCatalog or FKPCatalogMesh
    poles : list of int multipoles
    dk, kmin, kmax : k-binning
    second : optional cross mesh (same FKPCatalog geometry)
    """

    logger = logging.getLogger('ConvolvedFFTPower')

    def __init__(self, first, poles, second=None, Nmesh=None, kmin=0.,
                 kmax=None, dk=None):
        if isinstance(first, FKPCatalog):
            first = first.to_mesh(Nmesh=Nmesh)
        if not isinstance(first, FKPCatalogMesh):
            raise TypeError("first must be an FKPCatalog or "
                            "FKPCatalogMesh")
        if second is None:
            second = first
        self.first = first
        self.second = second
        self.comm = first.comm

        if np.isscalar(poles):
            poles = [poles]
        self.attrs = {
            'poles': sorted(poles),
            'dk': dk,
            'kmin': kmin,
            'kmax': kmax,
        }
        self.attrs['Nmesh'] = first.attrs['Nmesh'].copy()
        self.attrs['BoxSize'] = first.attrs['BoxSize']
        self.attrs['BoxCenter'] = first.attrs['BoxCenter']

        self.run()

    def run(self):
        pm = self.first.pm
        dk = 2 * np.pi / pm.BoxSize.min() if self.attrs['dk'] is None \
            else self.attrs['dk']
        kmin = self.attrs['kmin']
        kmax = self.attrs['kmax']
        if kmax is None:
            kmax = np.pi * pm.Nmesh.min() / pm.BoxSize.max() + dk / 2

        if dk > 0:
            kedges = np.arange(kmin, kmax, dk)
            kcoords = None
        else:
            kedges, kcoords = _find_unique_edges(pm, kmax)

        result = self._compute_multipoles(kedges)

        self.poles = BinnedStatistic(
            ['k'], [kedges], result, fields_to_sum=['modes'],
            coords=[kcoords], **self.attrs)
        self.edges = kedges

    def _compute_multipoles(self, kedges):
        pm = self.first.pm
        volume = float(np.prod(pm.BoxSize))

        poles = sorted(self.attrs['poles'])
        if 0 not in poles:
            poles = [0] + poles

        # odd multipoles under wide-angle (varying line of sight) need
        # the full complex spectrum — the hermitian (r2c) shortcut only
        # holds for even ell (reference: the dtype='c16' path)
        use_c2c = any(ell % 2 for ell in poles)
        from ...parallel.dfft import dist_fftn_c2c

        def forward(x):
            if use_c2c:
                return dist_fftn_c2c(x.astype(jnp.complex64
                                     if pm.dtype.itemsize <= 4 else
                                     jnp.complex128), pm.comm) \
                    * (1.0 / pm.Ntot)
            return pm.r2c(x)

        # the FKP density field
        rfield1 = self.first.compute(Nmesh=self.attrs['Nmesh'],
                                     mode='real')
        meta1 = dict(rfield1.attrs)
        self.attrs['alpha'] = meta1['alpha']

        transfer = compensation_transfer(self.first.resampler,
                                         self.first.interlaced)
        w_circ = pm.k_list(circular=True, full=use_c2c)

        c1 = forward(rfield1.value)
        c1 = transfer(w_circ, c1)
        A0_1 = c1 * volume

        if self.first is not self.second:
            rfield2 = self.second.compute(Nmesh=self.attrs['Nmesh'],
                                          mode='real')
            meta2 = dict(rfield2.attrs)
            if not np.allclose(meta1['alpha'], meta2['alpha'],
                               rtol=1e-3):
                # NBK103 (baselined, audited): raises between the two
                # forward FFTs' collectives, but alpha is global
                # catalog metadata identical on every rank — all ranks
                # raise together, the exception path is rank-uniform
                raise ValueError(
                    "cross-correlations require the same FKPCatalog "
                    "geometry (matching alpha)")
            c2 = transfer(w_circ, forward(rfield2.value)) * volume
            A0_2 = c2
        else:
            rfield2 = rfield1
            meta2 = meta1
            A0_2 = A0_1

        # normalization & shot noise from catalog sums
        for name in ['data', 'randoms']:
            self.attrs[name + '.norm'] = self.normalization(
                name, self.attrs['alpha'])
        if self.attrs['randoms.norm'] > 0:
            norm = 1.0 / self.attrs['randoms.norm']
            Adata = self.attrs['data.norm']
            Aran = self.attrs['randoms.norm']
            if not np.allclose(Adata, Aran, rtol=0.05):
                raise ValueError(
                    "normalizations from data (%.6g) and randoms (%.6g) "
                    "differ by more than 5%%; check the n(z) column "
                    "normalization and FKP weights" % (Adata, Aran))
        else:
            norm = 1.0

        # coordinate AXIS VECTORS only (a few KB): the full-mesh unit
        # vectors x/|x| and k/|k| are formed INSIDE the jitted
        # per-multipole program below, where XLA fuses them into the
        # Ylm weights. Building them eagerly here (as before round 4)
        # materialized six full-mesh f64 arrays and then baked them —
        # plus the density field — into every per-ell executable as
        # constants: ~35 GB of duplicated buffers at Nmesh=1024, the
        # OOM observed in the boss_like benchmark, and a guaranteed
        # HBM blow-up on a 16 GB TPU chip.
        N0, N1, N2 = pm.shape_real
        H = pm.cellsize
        offset = self.attrs['BoxCenter'] - pm.BoxSize / 2.0 + 0.5 * H

        # best-available precision, decided explicitly (NBK301): f8
        # under x64, f4 on TPU where jnp.float64 would demote silently
        _f8 = working_dtype('f8')
        xvec = [(jnp.arange(N0, dtype=_f8) * H[0]
                 + offset[0]).reshape(N0, 1, 1),
                (jnp.arange(N1, dtype=_f8) * H[1]
                 + offset[1]).reshape(1, N1, 1),
                (jnp.arange(N2, dtype=_f8) * H[2]
                 + offset[2]).reshape(1, 1, N2)]
        kvec = pm.k_list(dtype=_f8, full=use_c2c)

        cols = ['k'] + ['power_%d' % l for l in
                        sorted(self.attrs['poles'])] + ['modes']
        dtype = [('k', 'f8')] + [('power_%d' % l, 'c16') for l in
                                 sorted(self.attrs['poles'])] + \
            [('modes', 'i8')]
        result = np.empty(len(kedges) - 1, dtype=np.dtype(dtype))

        muedges = np.linspace(-1, 1, 2)
        density2 = rfield2.value

        cshape = (pm.shape_complex if not use_c2c else
                  (int(pm.Nmesh[1]), int(pm.Nmesh[0]),
                   int(pm.Nmesh[2])))

        def make_ell_term(ell):
            """Aell = sum_m FFT[F * Ylm(x/|x|)] * Ylm(k/|k|),
            compensated, * 4pi * volume — one jitted program per ell.
            The density is a real argument (not a baked constant) and
            the unit-vector meshes are fused into the Ylm weights."""
            def prog(dens):
                xn = jnp.sqrt(sum(x * x for x in xvec))
                xn = jnp.where(xn == 0, 1.0, xn)
                xu = [x / xn for x in xvec]
                kn = jnp.sqrt(sum(k * k for k in kvec))
                kn = jnp.where(kn == 0, jnp.inf, kn)
                ku = [k / kn for k in kvec]
                Aell = jnp.zeros(cshape, dtype=A0_1.dtype)
                for m in range(-ell, ell + 1):
                    Ylm = get_real_Ylm(ell, m)
                    wx = Ylm(xu[0], xu[1], xu[2])
                    ck = forward(dens * wx.astype(dens.dtype))
                    Aell = Aell + ck * Ylm(ku[0], ku[1], ku[2])
                Aell = transfer(w_circ, Aell)
                return Aell * (4 * np.pi * volume)
            # one program per ell BY DESIGN: each executes exactly
            # once, and memoizing across run() calls would pin the
            # fused Ylm/unit-vector constants (~GBs at Nmesh=1024) in
            # HBM for the life of the process
            return jax.jit(prog)   # nbkl: disable=NBK202

        proj_result = None
        for ell in poles[1:]:
            t0 = time.time()
            Aell = make_ell_term(ell)(density2)
            p3d = norm * A0_1 * jnp.conj(Aell)
            field = Field(p3d, pm, 'complex')
            proj, _ = project_to_basis(field, [kedges, muedges])
            result['power_%d' % ell][:] = np.squeeze(proj[2])
            self.logger.info("ell = %d done (%d FFTs, %.2fs)"
                             % (ell, 2 * ell + 1, time.time() - t0))
            proj_result = proj

        if 0 in self.attrs['poles']:
            p3d = norm * A0_1 * jnp.conj(A0_2)
            field = Field(p3d, pm, 'complex')
            proj, _ = project_to_basis(field, [kedges, muedges])
            result['power_0'][:] = np.squeeze(proj[2])
            proj_result = proj

        result['k'][:] = np.squeeze(proj_result[0])
        result['modes'][:] = np.squeeze(proj_result[3])

        self.attrs['shotnoise'] = self.shotnoise(self.attrs['alpha'])

        for key in ['data.W', 'randoms.W', 'data.N', 'randoms.N',
                    'data.num_per_cell', 'randoms.num_per_cell']:
            if key in meta1:
                self.attrs[key] = meta1[key]
        return result

    def normalization(self, name, alpha):
        """A = sum n(z) w_comp w_fkp1 w_fkp2 (alpha-weighted for the
        randoms); Beutler et al. 2014 eqs. 13-14 (reference :657-709)."""
        mesh1, mesh2 = self.first, self.second
        cat1 = mesh1.source[name]
        cat2 = mesh2.source[name]
        sel = jnp.asarray(cat1[mesh1.selection])
        comp = cat1[mesh1.comp_weight]
        nbar = cat2[mesh2.nbar]
        w1 = cat1[mesh1.fkp_weight]
        w2 = w1 if mesh1 is mesh2 else cat2[mesh2.fkp_weight]
        A = jnp.where(sel, nbar * comp * w1 * w2, 0.0).sum()
        A = float(A)
        if name == 'randoms':
            A *= alpha
        return A

    def shotnoise(self, alpha):
        """S = [sum_data (w_comp w_fkp)^2 + alpha^2 sum_randoms (...)^2]
        / randoms.norm (Beutler et al. 2014 eq. 15; reference
        :711-759)."""
        Pshot = 0.0
        mesh1, mesh2 = self.first, self.second
        for name in ['data', 'randoms']:
            cat1 = mesh1.source[name]
            cat2 = mesh2.source[name]
            sel = jnp.asarray(cat1[mesh1.selection])
            comp = cat1[mesh1.comp_weight]
            w1 = cat1[mesh1.fkp_weight]
            w2 = w1 if mesh1 is mesh2 else cat2[mesh2.fkp_weight]
            S = float(jnp.where(sel, comp ** 2 * w1 * w2, 0.0).sum())
            if name == 'randoms':
                S *= alpha ** 2
            Pshot += S
        if self.attrs['randoms.norm'] > 0:
            return Pshot / self.attrs['randoms.norm']
        return 0.0

    def to_pkmu(self, mu_edges, max_ell):
        """Rotate multipoles into P(k, mu) wedges (reference :282)."""
        from scipy.special import legendre
        from scipy.integrate import quad

        def coefficient(ell, mumin, mumax):
            return quad(lambda mu: legendre(ell)(mu), mumin,
                        mumax)[0] / (mumax - mumin)

        ells = list(range(0, max_ell + 1, 2))
        if any('power_%d' % ell not in self.poles for ell in ells):
            raise ValueError("need all even ells <= %d" % max_ell)

        dtype = np.dtype([('power', 'c8'), ('k', 'f8'), ('mu', 'f8')])
        data = np.zeros((self.poles.shape[0], len(mu_edges) - 1),
                        dtype=dtype)
        for imu, (lo, hi) in enumerate(zip(mu_edges[:-1], mu_edges[1:])):
            for ell in ells:
                data['power'][:, imu] += coefficient(ell, lo, hi) \
                    * self.poles['power_%d' % ell]
            data['k'][:, imu] = self.poles['k']
            data['mu'][:, imu] = 0.5 * (lo + hi)

        return BinnedStatistic(
            ['k', 'mu'], [self.poles.edges['k'], mu_edges], data,
            coords=[self.poles.coords['k'], None], **self.attrs)

    def save(self, output):
        import json
        with open(output, 'w') as ff:
            json.dump(self.__getstate__(), ff, cls=JSONEncoder)

    @classmethod
    def load(cls, output, comm=None, format='current'):
        """Load a saved result; ``format='pre000305'`` reads the legacy
        layout of files written by nbodykit < 0.3.5 (reference
        fkp.py:377-406)."""
        import json
        with open(output, 'r') as ff:
            state = json.load(ff, cls=JSONDecoder)
        self = object.__new__(cls)
        if format == 'current':
            self.__setstate__(state)
        elif format == 'pre000305':
            self.__setstate_pre000305__(state)
        else:
            raise ValueError("format must be 'current' or 'pre000305'")
        return self

    def __getstate__(self):
        return dict(edges=self.edges,
                    poles=self.poles.__getstate__(),
                    attrs=self.attrs)

    def __setstate__(self, state):
        self.attrs = state['attrs']
        self.edges = state['edges']
        self.poles = BinnedStatistic.from_state(state['poles'])

    def __setstate_pre000305__(self, state):
        """Files generated before nbodykit 0.3.5 store the poles as a
        raw structured array + flat edges (reference fkp.py:349-354)."""
        edges = state['edges']
        self.attrs = state['attrs']
        self.edges = edges
        self.poles = BinnedStatistic(['k'], [edges], state['poles'],
                                     fields_to_sum=['modes'])
