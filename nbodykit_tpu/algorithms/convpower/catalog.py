"""FKPCatalog: joint data+randoms container for survey power spectra.

Reference: ``nbodykit/algorithms/convpower/catalog.py:30`` — a
MultipleSpeciesCatalog of ('data', 'randoms') that computes the shared
Cartesian bounding box from the randoms and hands off to FKPCatalogMesh.
"""

import numpy as np
import jax.numpy as jnp

from ...source.catalog.species import MultipleSpeciesCatalog
from ...utils import as_numpy


def FKPWeightFromNbar(P0, nbar):
    """w_FKP = 1 / (1 + P0 * n(z)) (FKP 1994)."""
    if P0 != 0:
        return 1.0 / (1.0 + P0 * nbar)
    return 1.0


class FKPCatalog(MultipleSpeciesCatalog):
    """data + randoms with FKP weighting and a shared bounding box.

    Parameters mirror the reference (convpower/catalog.py:75): BoxSize
    (else computed from the randoms' extent), BoxPad, P0 (to build
    FKPWeight from the ``nbar`` column).
    """

    def __init__(self, data, randoms, BoxSize=None, BoxPad=0.02,
                 P0=None, nbar='NZ'):
        if randoms is None:
            randoms = data[:0]
        MultipleSpeciesCatalog.__init__(self, ['data', 'randoms'],
                                        data, randoms)
        for name in self.species:
            if nbar not in self[name]:
                raise ValueError("column %r is not defined in %r"
                                 % (nbar, name))
        self.nbar = nbar

        for name in self.species:
            if P0 is not None:
                self[name]['FKPWeight'] = FKPWeightFromNbar(
                    P0, self[name][self.nbar])
            elif 'FKPWeight' not in self[name]:
                self[name]['FKPWeight'] = jnp.ones(len(self[name]))

        if BoxSize is not None and np.isscalar(BoxSize):
            BoxSize = np.ones(3) * BoxSize
        self.attrs['BoxSize'] = BoxSize
        if np.isscalar(BoxPad):
            BoxPad = np.ones(3) * BoxPad
        self.attrs['BoxPad'] = BoxPad

    def _define_bbox(self, position, selection, species):
        """BoxSize (padded extent) and BoxCenter from the positions of
        ``species`` (reference :110+)."""
        cat = self[species]
        pos = as_numpy(cat[position])
        sel = as_numpy(cat[selection]).astype(bool)
        pos = pos[sel]
        if len(pos) == 0:
            raise ValueError("no selected objects in %r to define the "
                             "bounding box" % species)
        pos_min = pos.min(axis=0)
        pos_max = pos.max(axis=0)
        if np.isinf(pos_min).any() or np.isinf(pos_max).any():
            raise ValueError("infinite position range in %r" % species)

        delta = np.abs(pos_max - pos_min)
        BoxCenter = 0.5 * (pos_min + pos_max)
        if self.attrs['BoxSize'] is None:
            delta = delta * (1.0 + self.attrs['BoxPad'])
            BoxSize = np.ceil(delta)
        else:
            BoxSize = self.attrs['BoxSize']
        return BoxSize, BoxCenter

    def to_mesh(self, Nmesh=None, BoxSize=None, BoxCenter=None,
                dtype='f8', interlaced=False, compensated=False,
                resampler='cic', fkp_weight='FKPWeight',
                comp_weight='Weight', selection='Selection',
                position='Position', bbox_from_species=None, nbar=None):
        """An FKPCatalogMesh painting data - alpha*randoms.

        The mesh itself is stored real; ConvolvedFFTPower switches to
        the full-complex (c2c) spectrum automatically when odd
        multipoles are requested (the reference's dtype='c16' analog).
        """
        from .catalogmesh import FKPCatalogMesh
        if nbar is None:
            nbar = self.nbar
        if Nmesh is None:
            Nmesh = self.attrs.get('Nmesh', None)
            if Nmesh is None:
                raise ValueError("pass Nmesh to to_mesh")
        if bbox_from_species is None:
            bbox_from_species = 'randoms' if len(self['randoms']) > 0 \
                else 'data'
        box, center = self._define_bbox(position, selection,
                                        bbox_from_species)
        if BoxSize is None:
            BoxSize = box
        if BoxCenter is None:
            BoxCenter = center
        if dtype in ('c16', 'c8'):
            dtype = {'c16': 'f8', 'c8': 'f4'}[dtype]

        return FKPCatalogMesh(self, BoxSize=BoxSize, BoxCenter=BoxCenter,
                              Nmesh=Nmesh, dtype=dtype,
                              selection=selection,
                              comp_weight=comp_weight,
                              fkp_weight=fkp_weight, nbar=nbar,
                              position=position, interlaced=interlaced,
                              compensated=compensated,
                              resampler=resampler)
