"""FKPCatalogMesh: paint the FKP density field.

Reference: ``nbodykit/algorithms/convpower/catalogmesh.py:7`` — paints
F(x) = w_fkp * [w_comp n_data - alpha w_comp n_randoms] / cellvolume,
with positions re-centered to [-L/2, L/2].
"""

import numpy as np
import jax.numpy as jnp

from ...source.mesh.species import MultipleSpeciesCatalogMesh
from ...source.mesh.catalog import CatalogMesh
from ...base.mesh import Field
from ...utils import as_numpy


class FKPCatalogMesh(MultipleSpeciesCatalogMesh):

    def __init__(self, source, BoxSize, BoxCenter, Nmesh, dtype,
                 selection, comp_weight, fkp_weight, nbar, value='Value',
                 position='Position', interlaced=False, compensated=False,
                 resampler='cic'):
        from .catalog import FKPCatalog
        if not isinstance(source, FKPCatalog):
            raise TypeError("FKPCatalogMesh requires an FKPCatalog")

        self.attrs = dict(source.attrs)
        self.attrs['BoxSize'] = np.ones(3) * BoxSize
        self.attrs['BoxCenter'] = np.ones(3) * BoxCenter

        self._uncentered_position = position
        self.comp_weight = comp_weight
        self.fkp_weight = fkp_weight
        self.nbar = nbar

        MultipleSpeciesCatalogMesh.__init__(
            self, source=source, BoxSize=BoxSize, Nmesh=Nmesh,
            dtype=dtype, weight='_TotalWeight', value=value,
            selection=selection, position='_RecenteredPosition',
            interlaced=interlaced, compensated=compensated,
            resampler=resampler)

    def RecenteredPosition(self, name):
        """Positions shifted by -BoxCenter, i.e. into [-L/2, L/2]
        (reference :206). The ParticleMesh grid covers [0, L); shift by
        +L/2 so painting sees [0, L)."""
        pos = self.source[name][self._uncentered_position]
        center = jnp.asarray(self.attrs['BoxCenter'], pos.dtype)
        return pos - center

    def TotalWeight(self, name):
        """comp_weight * fkp_weight (reference :217)."""
        return (self.source[name][self.comp_weight]
                * self.source[name][self.fkp_weight])

    def weighted_total(self, name):
        """W = sum of selected completeness weights (reference
        weighted_total)."""
        cat = self.source[name]
        sel = cat[self.selection]
        w = cat[self.comp_weight]
        return float(jnp.where(sel, w, 0.0).sum())

    def __getitem__(self, species):
        if species not in self.source.species:
            raise KeyError(species)
        cat = self.source[species]
        # provide derived columns on a shallow view of the species
        half = jnp.asarray(self.attrs['BoxSize'] / 2.0)
        view = cat.view()
        pos = self.RecenteredPosition(species)
        view['_RecenteredPosition'] = pos + jnp.asarray(
            half, pos.dtype)  # paint grid covers [0, L)
        view['_TotalWeight'] = self.TotalWeight(species)
        return CatalogMesh(
            view, Nmesh=self.attrs['Nmesh'], BoxSize=self.attrs['BoxSize'],
            dtype=self.pm.dtype.str, interlaced=self.interlaced,
            compensated=self.compensated, resampler=self.resampler,
            position='_RecenteredPosition', weight='_TotalWeight',
            value=self.value, selection=self.selection)

    def to_real_field(self):
        """The FKP density field (number density units); attrs carry
        data.W / randoms.W / alpha and per-species paint meta-data."""
        attrs = {}
        for name in self.source.species:
            attrs[name + '.W'] = self.weighted_total(name)
        attrs['alpha'] = attrs['data.W'] / attrs['randoms.W'] \
            if attrs['randoms.W'] > 0 else 1.0

        data_field = self['data'].to_real_field(normalize=False)
        for k, v in data_field.attrs.items():
            attrs['data.' + k] = v
        total = data_field.value

        if len(self.source['randoms']) > 0:
            ran_field = self['randoms'].to_real_field(normalize=False)
            for k, v in ran_field.attrs.items():
                attrs['randoms.' + k] = v
            total = total - attrs['alpha'] * ran_field.value

        vol_per_cell = float(np.prod(self.attrs['BoxSize'] /
                                     self.attrs['Nmesh']))
        total = total / vol_per_cell
        attrs.pop('data.shotnoise', None)
        attrs.pop('randoms.shotnoise', None)
        return Field(total, self.pm, 'real', attrs)
