"""FiberCollisions: spectroscopic fiber assignment simulation.

Reference: ``nbodykit/algorithms/fibercollisions.py:8`` — angular FOF
groups at the collision radius, then fiber assignment minimizing the
number of collided objects (Guo et al. 2012 procedure): pairs collide
one random member; larger multiplets iteratively remove the member with
the most collisions (ties broken by fewest neighbor collisions, then
randomly).

The angular FOF reuses :class:`..algorithms.fof.FOF` on unit-sphere
Cartesian coordinates with an absolute chord linking length; the
group-by-group assignment is a host-side loop over (small) groups.
"""

import logging

import numpy as np

from ..source.catalog.array import ArrayCatalog
from ..transform import SkyToUnitSphere
from ..utils import as_numpy
from .fof import FOF


class FiberCollisions(object):
    """Assign fibers to (ra, dec) objects.

    Results in :attr:`labels` — an ArrayCatalog with Label (angular
    group), Collided (0/1), NeighborID (global index of the nearest
    uncollided neighbor for collided objects, else -1).
    """

    logger = logging.getLogger('FiberCollisions')

    def __init__(self, ra, dec, collision_radius=62. / 60. / 60.,
                 seed=None, degrees=True, comm=None):
        ra = as_numpy(ra)
        dec = as_numpy(dec)
        self._collision_radius_rad = np.radians(
            collision_radius if degrees else np.degrees(
                collision_radius))
        # chord length corresponding to the angular radius
        self._chord = 2 * np.sin(0.5 * self._collision_radius_rad)
        if seed is None:
            seed = np.random.randint(0, 2 ** 31 - 1)
        self.attrs = dict(collision_radius=collision_radius, seed=seed)

        pos = np.asarray(SkyToUnitSphere(ra, dec))
        # place the unit sphere inside a non-wrapping box for FOF
        shifted = pos + 2.0
        cat = ArrayCatalog({'Position': shifted}, BoxSize=4.0)
        self.comm = cat.comm

        fof = FOF(cat, linking_length=self._chord, nmin=2,
                  absolute=True)
        labels = np.asarray(fof.labels)

        collided, neighbors = self._assign_fibers(pos, labels, seed)

        N1 = int((collided == 0).sum())
        N2 = int(collided.sum())
        self.logger.info("population 1 (clean) = %d, population 2 "
                         "(collided) = %d, fraction = %.4f"
                         % (N1, N2, N2 / max(N1 + N2, 1)))

        self.labels = ArrayCatalog(
            {'Label': labels, 'Collided': collided.astype('i4'),
             'NeighborID': neighbors.astype('i4')})
        self.labels.attrs.update(self.attrs)

    def _assign_fibers(self, pos, labels, seed):
        rng = np.random.RandomState(seed)
        N = len(pos)
        collided = np.zeros(N, dtype='i4')
        neighbors = np.full(N, -1, dtype='i4')

        for lab in np.unique(labels):
            if lab == 0:
                continue
            members = np.flatnonzero(labels == lab)
            if len(members) == 2:
                which = rng.choice(2)
                collided[members[which]] = 1
                neighbors[members[which]] = members[which ^ 1]
                continue
            coll_ids, neigh = self._assign_multiplet(
                pos[members], rng)
            collided[members[coll_ids]] = 1
            for ci, ni in zip(coll_ids, neigh):
                neighbors[members[ci]] = members[ni]
        return collided, neighbors

    def _assign_multiplet(self, P, rng):
        """Greedy removal for groups of size > 2 (reference
        _assign_multiplets, fibercollisions.py:232)."""
        n = len(P)
        group_ids = list(range(n))
        collided_ids = []
        d = np.sqrt(((P[:, None, :] - P[None, :, :]) ** 2).sum(-1))
        np.fill_diagonal(d, np.inf)
        while len(group_ids) > 1:
            sub = d[np.ix_(group_ids, group_ids)]
            collisions = sub <= self._chord
            ncoll = collisions.sum(axis=0)
            if ncoll.max() == 0:
                break
            nother = np.array([ncoll[collisions[:, i]].sum()
                               for i in range(len(group_ids))])
            idx = np.flatnonzero(ncoll == ncoll.max())
            ii = rng.choice(np.flatnonzero(
                nother[idx] == nother[idx].min()))
            collided_index = idx[ii]
            cid = group_ids.pop(collided_index)
            if ncoll[collided_index] > 0:
                collided_ids.append(cid)

        uncollided = [i for i in range(n) if i not in collided_ids]
        neigh = []
        for i in sorted(collided_ids):
            neigh.append(uncollided[int(np.argmin(d[i][uncollided]))])
        return sorted(collided_ids), neigh
