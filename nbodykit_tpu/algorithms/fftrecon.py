"""FFTRecon: standard BAO reconstruction of the density field.

Reference: ``nbodykit/algorithms/fftrecon.py:11``. Capability parity:
LGS (Lagrangian growth shift), LF2, and LRR schemes; RSD reversion via
(bias, f, los); Gaussian smoothing of the displacement solve.

TPU redesign: the displacement solve (paint -> r2c -> smoothed
1j k / k^2 kernel -> c2r -> readout) is jnp ops over the sharded mesh;
the three component solves share one forward FFT.
"""

import logging
import warnings

import numpy as np
import jax.numpy as jnp

from ..base.mesh import MeshSource, Field
from ..base.catalog import CatalogSourceBase
from ..pmesh import ParticleMesh


class FFTRecon(MeshSource):
    """Reconstructed density mesh from data + randoms catalogs.

    Parameters (reference fftrecon.py:24-62): data, ran, Nmesh, bias, f,
    los, R (smoothing radius), position column, revert_rsd_random,
    scheme in {'LGS', 'LF2', 'LRR'}, BoxSize.
    """

    logger = logging.getLogger('FFTRecon')

    def __init__(self, data, ran, Nmesh, bias=1.0, f=0.0, los=[0, 0, 1],
                 R=20, position='Position', revert_rsd_random=False,
                 scheme='LGS', BoxSize=None, resampler='cic'):
        if scheme not in ('LGS', 'LF2', 'LRR'):
            raise ValueError("scheme must be LGS, LF2 or LRR")
        if not isinstance(data, CatalogSourceBase) or \
                not isinstance(ran, CatalogSourceBase):
            raise TypeError("data and ran must be catalogs")

        if Nmesh is None:
            Nmesh = data.attrs['Nmesh']
        if BoxSize is None:
            BoxSize = data.attrs['BoxSize']

        los = np.array(los, dtype='f8')
        los /= (los ** 2).sum() ** 0.5

        MeshSource.__init__(self, Nmesh, BoxSize, dtype='f4',
                            comm=data.comm)
        if (self.pm.BoxSize / self.pm.Nmesh).max() > R:
            warnings.warn("smoothing radius is smaller than the mesh "
                          "cell; expect numerical noise")

        self.attrs.update(bias=bias, f=f, los=los, R=R, scheme=scheme,
                          revert_rsd_random=bool(revert_rsd_random))
        self.data = data
        self.ran = ran
        self.position = position
        self.resampler = resampler

    def to_real_field(self):
        return self.run()

    def run(self):
        s_d, s_r = self._compute_s()
        return self._helper_paint(s_d, s_r)

    def _paint_overdensity(self, cat, shift):
        """Paint cat at (Position - shift), normalized by mean density
        (reference work_with, fftrecon.py:144-169)."""
        pm = self.pm
        pos = cat[self.position].astype(jnp.float32)
        if shift is not None:
            pos = pos - shift
        field = pm.paint(pos, 1.0, resampler=self.resampler)
        nbar = cat.csize / pm.Ntot
        return field / nbar

    def _displacement_kernels(self):
        """The three smoothed Zel'dovich solve kernels
        1j k_d / k^2 * exp(-k^2 R^2 / 2) / (b (1 + f/b mu^2))."""
        pm = self.pm
        kx, ky, kz = pm.k_list()
        k2 = kx ** 2 + ky ** 2 + kz ** 2
        k2s = jnp.where(k2 == 0, 1.0, k2)
        los = self.attrs['los']
        mu = (kx * los[0] + ky * los[1] + kz * los[2]) / jnp.sqrt(k2s)
        smooth = jnp.exp(-0.5 * k2s * self.attrs['R'] ** 2)
        frac = self.attrs['bias'] * (
            1.0 + self.attrs['f'] / self.attrs['bias'] * mu ** 2)
        base = smooth / frac
        ks = [kx, ky, kz]
        return [jnp.where(k2 == 0, 0.0, 1j * ks[d] / k2s * base)
                for d in range(3)]

    def _compute_s(self):
        pm = self.pm
        delta_d = self._paint_overdensity(self.data, None)
        delta_k = pm.r2c(delta_d)
        kernels = self._displacement_kernels()

        def solve(cat):
            pos = cat[self.position].astype(jnp.float32)
            comps = []
            for d in range(3):
                disp = pm.c2r(delta_k * kernels[d])
                comps.append(pm.readout(disp, pos,
                                        resampler=self.resampler))
            return jnp.stack(comps, axis=-1)

        s_d = solve(self.data)
        s_r = solve(self.ran)

        los = jnp.asarray(self.attrs['los'], s_d.dtype)
        # revert RSD in the data displacement (reference :260)
        s_d = s_d * (1.0 + los * self.attrs['f'])
        if self.attrs['revert_rsd_random']:
            s_r = s_r * (1.0 + los * self.attrs['f'])
        return s_d, s_r

    def _helper_paint(self, s_d, s_r):
        """Combine shifted paints per scheme (reference :172-215)."""
        delta_s_r = self._paint_overdensity(self.ran, s_r)

        def LGS():
            delta_s_d = self._paint_overdensity(self.data, s_d)
            return delta_s_d - delta_s_r

        def LRR():
            delta_s_nr = self._paint_overdensity(self.ran, -s_r)
            delta_d = self._paint_overdensity(self.data, None)
            return delta_d - 0.5 * (delta_s_nr + delta_s_r)

        if self.attrs['scheme'] == 'LGS':
            out = LGS()
        elif self.attrs['scheme'] == 'LRR':
            out = LRR()
        else:  # LF2
            out = 3.0 / 7.0 * LGS() + 4.0 / 7.0 * LRR()

        return Field(out, self.pm, 'real')
