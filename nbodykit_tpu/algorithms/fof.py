"""FOF: friends-of-friends halo finder.

Reference: ``nbodykit/algorithms/fof.py:10`` — domain-decomposed kdcount
FOF + iterative cross-rank label merging (:289-337), then halo property
reduction (:427-727).

TPU redesign (no kd-tree, no ragged recursion): a *grid-hash
label-propagation* FOF that is one jitted XLA program:

1. hash particles to cells of size = linking length; sort by cell
   (cells are contiguous ranges after the sort);
2. labels start as particle indices; each sweep takes, for every
   particle, the min label over all particles of the 27 neighbor cells
   within the linking length (fixed per-cell capacity K = max occupancy,
   so shapes are static), followed by pointer-jumping (path halving),
   inside a lax.while_loop until a fixpoint;
3. halo properties (Length, periodic-aware CMPosition, CMVelocity) are
   segment reductions over the final labels; halos are relabeled by
   descending size with label 0 = below ``nmin`` (matching the
   reference's _assign_labels ordering semantics, :197-287).

The sweep cost is N * 27 * K distance checks, fully vectorized; the
while_loop converges in O(log diameter) sweeps thanks to path halving.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..base.catalog import CatalogSourceBase
from ..utils import as_numpy


def _fof_labels(pos, BoxSize, ll, periodic=True):
    """FOF label computation (jittable sweeps inside).

    pos : (N, 3) positions (host/device); BoxSize : (3,) floats;
    ll : linking length; periodic : wrap at the box boundary

    Returns (N,) int32 root labels (min particle index per group, in the
    cell-sorted ordering) mapped back to input order.
    """
    from ..ops.gridhash import GridHash
    N = pos.shape[0]
    grid = GridHash(np.asarray(pos), BoxSize, ll, periodic=periodic,
                    max_ncell=256)
    order = jnp.asarray(grid.order)
    pos_s = grid.pos_s
    ci_s = grid.cell_of(pos_s)

    ll2 = jnp.asarray(ll * ll, pos_s.dtype)

    def neighbor_min(labels):
        """For each particle: min label among particles within ll."""
        def body(best, j, valid, d, r2):
            ok = valid & (r2 <= ll2)
            cand = jnp.where(ok, labels[j], best)
            return jnp.minimum(best, cand)
        return grid.fold(pos_s, ci_s, body, labels)

    labels0 = jnp.arange(N, dtype=jnp.int32)

    def body(state):
        labels, _ = state
        new = neighbor_min(labels)
        # pointer jumping (path halving) — labels are particle indices
        new = jnp.minimum(new, new[new])
        new = jnp.minimum(new, new[new])
        changed = jnp.any(new != labels)
        return new, changed

    def cond(state):
        return state[1]

    labels, _ = jax.lax.while_loop(
        cond, body, (labels0, jnp.asarray(True)))

    # map back to input order: label value refers to sorted index; remap
    # to a stable id = original index of the root particle
    root_orig = order[labels]
    out = jnp.empty(N, dtype=jnp.int32).at[order].set(
        root_orig.astype(jnp.int32))
    return out


class FOF(object):
    """Friends-of-friends groups of a CatalogSource.

    Parameters (reference fof.py:46): source, linking_length (in mean
    inter-particle separation units unless ``absolute=True``), nmin
    (minimum group size), periodic.

    Attributes
    ----------
    labels : (N,) halo label per particle; 0 = not in a group of size
        >= nmin; halos ordered by descending size (label 1 is the
        largest), matching the reference's convention.
    """

    logger = logging.getLogger('FOF')

    def __init__(self, source, linking_length, nmin, absolute=False,
                 periodic=True):
        if 'Position' not in source:
            raise ValueError("source must have a Position column")
        self.comm = source.comm
        self._source = source
        self.attrs = {
            'linking_length': linking_length,
            'nmin': nmin,
            'absolute': absolute,
            'periodic': periodic,
        }
        if 'BoxSize' in source.attrs:
            self.attrs['BoxSize'] = np.ones(3) * np.asarray(
                source.attrs['BoxSize'], dtype='f8')
        else:
            raise ValueError("source must define attrs['BoxSize']")

        if not absolute:
            mean_sep = (np.prod(self.attrs['BoxSize'])
                        / source.csize) ** (1. / 3)
            linking_length = linking_length * mean_sep
        self._ll = float(linking_length)

        self.labels = self.run()

    def run(self):
        pos = as_numpy(self._source['Position'])
        BoxSize = self.attrs['BoxSize']
        roots = _fof_labels(pos, BoxSize, self._ll,
                            periodic=self.attrs['periodic'])

        # compact + size-ordered halo labels (reference _assign_labels)
        roots_np = as_numpy(roots)
        uniq, inv, counts = np.unique(roots_np, return_inverse=True,
                                      return_counts=True)
        nmin = self.attrs['nmin']
        # order by descending count among groups >= nmin
        eligible = counts >= nmin
        order = np.argsort(-counts[eligible], kind='stable')
        label_map = np.zeros(len(uniq), dtype='i8')
        label_map[np.flatnonzero(eligible)[order]] = \
            np.arange(1, eligible.sum() + 1)
        labels = label_map[inv]
        self._halo_count = int(eligible.sum())
        return jnp.asarray(labels)

    def find_features(self, peakcolumn=None):
        """The halo catalog as a BinnedStatistic-free ArrayCatalog with
        Length / CMPosition / CMVelocity (+ peak position when
        ``peakcolumn`` given); reference fof_catalog (fof.py:427-533)."""
        from ..source.catalog.array import ArrayCatalog
        data = fof_catalog(self._source, self.labels,
                           self._halo_count + 1,
                           self.attrs['BoxSize'],
                           periodic=self.attrs['periodic'],
                           peakcolumn=peakcolumn)
        cat = ArrayCatalog(data, comm=self.comm, **self.attrs)
        return cat

    def to_halos(self, particle_mass, cosmo, redshift, mdef='vir'):
        """A HaloCatalog with Position/Velocity/Mass (reference
        fof.py:130)."""
        from ..source.catalog.halos import HaloCatalog
        features = self.find_features()
        # drop label 0 (unbound particles)
        sel = np.arange(1, len(features))
        data = {
            'Position': features['CMPosition'][1:],
            'Velocity': features['CMVelocity'][1:],
            'Length': features['Length'][1:],
        }
        from ..source.catalog.array import ArrayCatalog
        attrs = dict(self.attrs)
        attrs.update(particle_mass=particle_mass, redshift=redshift,
                     mdef=mdef)
        cat = ArrayCatalog(data, comm=self.comm, **attrs)
        return HaloCatalog(cat, cosmo=cosmo, redshift=redshift,
                           mdef=mdef, mass='Mass', position='Position',
                           velocity='Velocity',
                           particle_mass=particle_mass)


def fof_catalog(source, labels, nhalo, BoxSize, periodic=True,
                peakcolumn=None):
    """Per-halo reductions: Length, periodic center-of-mass position,
    mean velocity (reference fof_catalog/centerofmass,
    fof.py:427-727)."""
    labels = jnp.asarray(labels)
    pos = jnp.asarray(source['Position'])
    box = jnp.asarray(BoxSize, pos.dtype)

    length = jnp.bincount(labels, length=nhalo)

    # periodic center of mass: average offsets relative to a reference
    # particle per halo (the reference uses the same relative-unwrap
    # trick, fof.py:589-643)
    first_idx = jnp.zeros(nhalo, dtype=jnp.int32).at[labels[::-1]].set(
        jnp.arange(len(labels) - 1, -1, -1, dtype=jnp.int32))
    ref = pos[first_idx][labels]
    d = pos - ref
    if periodic:
        d = d - jnp.round(d / box) * box
    dsum = jnp.zeros((nhalo, 3), pos.dtype).at[labels].add(d)
    lsafe = jnp.maximum(length, 1).astype(pos.dtype)[:, None]
    cm = pos[first_idx] + dsum / lsafe
    if periodic:
        cm = jnp.mod(cm, box)

    data = {
        'Length': length,
        'CMPosition': cm,
    }

    if 'Velocity' in source:
        vel = jnp.asarray(source['Velocity'])
        vsum = jnp.zeros((nhalo, 3), vel.dtype).at[labels].add(vel)
        data['CMVelocity'] = vsum / lsafe
    else:
        data['CMVelocity'] = jnp.zeros((nhalo, 3), pos.dtype)

    if peakcolumn is not None and peakcolumn in source:
        density = jnp.asarray(source[peakcolumn])
        # argmax per halo via segment max on (density, index) pairs
        neg = jnp.full(nhalo, -jnp.inf, dtype=density.dtype)
        dmax = neg.at[labels].max(density)
        ispeak = density >= dmax[labels]
        # peak particle per halo; non-peak particles scatter into a
        # spare bucket (nhalo) so they cannot corrupt a real halo
        peak_idx = jnp.zeros(nhalo + 1, jnp.int32).at[
            jnp.where(ispeak, labels, nhalo)].max(
            jnp.arange(len(labels), dtype=jnp.int32))[:nhalo]
        data['PeakPosition'] = pos[peak_idx]
        if 'Velocity' in source:
            data['PeakVelocity'] = jnp.asarray(
                source['Velocity'])[peak_idx]

    return {k: as_numpy(v) for k, v in data.items()}


class HaloLabelCatalog(CatalogSourceBase):
    pass
