"""FOF: friends-of-friends halo finder.

Reference: ``nbodykit/algorithms/fof.py:10`` — domain-decomposed kdcount
FOF + iterative cross-rank label merging (:289-337), then halo property
reduction (:427-727).

TPU redesign (no kd-tree, no ragged recursion): a *grid-hash
label-propagation* FOF that is one jitted XLA program:

1. hash particles to cells of size = linking length, sort by cell and
   locate neighbor cells by binary search (ops/devicehash.py — no
   dense cell table, so cells are never coarser than ll);
2. labels start as particle indices; each sweep takes, for every
   particle, the min label over all particles of the 27 neighbor cells
   within the linking length (slot loop = while_loop bounded by the
   max referenced-cell occupancy), followed by pointer-jumping (path
   halving), inside a lax.while_loop until a fixpoint;
3. halo properties (Length, periodic-aware CMPosition, CMVelocity) are
   segment reductions over the final labels; halos are relabeled by
   descending size with label 0 = below ``nmin`` (matching the
   reference's _assign_labels ordering semantics, :197-287).

With a device mesh active the same sweep runs domain-decomposed
(:func:`_fof_labels_distributed`): slab routing with ghost copies and
an exchange-based cross-device label merge.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..utils import as_numpy


def _fof_labels(pos, BoxSize, ll, periodic=True):
    """FOF label computation, single device.

    pos : (N, 3) positions (host/device); BoxSize : (3,) floats;
    ll : linking length; periodic : wrap at the box boundary

    Returns (N,) int32 root labels (the index of one canonical member
    per group), in input order. Delegates to the in-graph grid hash
    (:func:`...ops.devicehash.local_fof_labels`) — binary-search cell
    lookup with exactly ll-sized cells, so the sweep cost tracks the
    true local density instead of a capped-cell-size occupancy.
    """
    from ..ops.devicehash import local_fof_labels
    pos = jnp.asarray(pos)
    N = pos.shape[0]
    valid = jnp.ones(N, dtype=bool)
    box = np.asarray(BoxSize, dtype='f8')
    return jax.jit(
        lambda p, v: local_fof_labels(p, v, box, float(ll),
                                      periodic=periodic))(pos, valid)


def _fof_labels_distributed(pos, BoxSize, ll, mesh, periodic=True,
                            max_ncell=4096):
    """Domain-decomposed FOF labels over the device mesh.

    The reference's parallel FOF (nbodykit/algorithms/fof.py:339-413):
    GridND decompose with smoothing=ll ghosts -> local kdcount FOF ->
    iterated cross-rank label merge until fixpoint. TPU-native shape:

    1. route particles to x-slab owners, ghost-copying the lower-margin
       band to the lower neighbor (every linking pair is then fully
       visible on one device) — :func:`...parallel.domain.slab_route`;
    2. per device, ONE in-graph grid-hash FOF finds the local connected
       components (:func:`...ops.devicehash.local_fof_labels`) — the
       component structure is position-determined and never changes;
    3. iterate: broadcast per-particle labels to all copies (re-using
       the frozen exchange plan), per-component segment-min inside
       shard_map, min-reduce back to each particle's owner slot —
       shared ghost copies stitch components across devices exactly as
       the reference's layout.gather(minid, fmin)/exchange loop
       (fof.py:311-337). Converges in O(slabs-spanned) rounds.

    Returns (N,) int32 — min global particle index of each particle's
    group, as a sharded global array. Everything stays distributed; no
    device ever holds the full Position array.
    """
    from ..parallel.domain import (slab_route, scatter_reduce_by_index,
                                   padded_size, INT32_BIG)
    from ..parallel.runtime import AXIS, mesh_size, shard_leading
    from ..ops.devicehash import local_fof_labels
    from jax.sharding import PartitionSpec as P

    nproc = mesh_size(mesh)
    N = int(pos.shape[0])
    box = np.asarray(BoxSize, dtype='f8')
    pos = jnp.asarray(pos)

    # balance=True re-tiles slab widths from the particle histogram
    # (the reference's domain.loadbalance, fof.py:399) so a clustered
    # catalog spreads across devices instead of blowing up exchange
    # capacity on one of them
    route, f, live = slab_route(pos, box, ll, mesh, ghosts='down',
                                periodic=periodic, balance=True)
    gid = shard_leading(mesh, jnp.arange(N, dtype=jnp.int32))
    pos_f = jnp.concatenate([pos] * f)
    gid_f = jnp.concatenate([gid] * f)
    (pos_r, gid_r, live_r), ok, _ = route.exchange([pos_f, gid_f, live])
    work = ok & live_r

    ll_f = float(ll)

    # 2. local components (once)
    if nproc > 1:
        root = jax.jit(jax.shard_map(
            lambda p, v: local_fof_labels(p, v, box, ll_f,
                                          periodic=periodic,
                                          max_ncell=max_ncell,
                                          axis_name=AXIS),
            mesh=mesh, in_specs=(P(AXIS, None), P(AXIS)),
            out_specs=P(AXIS)))(pos_r, work)
    else:
        root = jax.jit(lambda p, v: local_fof_labels(
            p, v, box, ll_f, periodic=periodic,
            max_ncell=max_ncell))(pos_r, work)

    # 3. label merge loop
    padded, _ = padded_size(N, nproc)
    glab = shard_leading(mesh, jnp.arange(padded, dtype=jnp.int32))

    def seg_min(lab_l, root_l, work_l):
        big = jnp.asarray(INT32_BIG, jnp.int32)
        v = jnp.where(work_l, lab_l, big)
        comp = jnp.full(lab_l.shape[0], big, jnp.int32).at[root_l].min(v)
        return jnp.where(work_l, comp[root_l], big)

    if nproc > 1:
        seg_min_g = jax.jit(jax.shard_map(
            seg_min, mesh=mesh, in_specs=(P(AXIS), P(AXIS), P(AXIS)),
            out_specs=P(AXIS)))
    else:
        seg_min_g = jax.jit(seg_min)

    while True:
        lab_f = jnp.concatenate([glab[:N]] * f)
        (lab_r,), ok2, _ = route.exchange([lab_f])
        new = seg_min_g(lab_r, root, work)
        glab_new = scatter_reduce_by_index(
            gid_r, new, N, mesh, op='min', valid=work, init=glab)
        changed = bool(jnp.any(glab_new != glab))
        glab = glab_new
        if not changed:
            break
    return glab[:N]


class FOF(object):
    """Friends-of-friends groups of a CatalogSource.

    Parameters (reference fof.py:46): source, linking_length (in mean
    inter-particle separation units unless ``absolute=True``), nmin
    (minimum group size), periodic.

    Attributes
    ----------
    labels : (N,) halo label per particle; 0 = not in a group of size
        >= nmin; halos ordered by descending size (label 1 is the
        largest), matching the reference's convention.
    """

    logger = logging.getLogger('FOF')

    def __init__(self, source, linking_length, nmin, absolute=False,
                 periodic=True):
        if 'Position' not in source:
            raise ValueError("source must have a Position column")
        self.comm = source.comm
        self._source = source
        self.attrs = {
            'linking_length': linking_length,
            'nmin': nmin,
            'absolute': absolute,
            'periodic': periodic,
        }
        if 'BoxSize' in source.attrs:
            self.attrs['BoxSize'] = np.ones(3) * np.asarray(
                source.attrs['BoxSize'], dtype='f8')
        else:
            raise ValueError("source must define attrs['BoxSize']")

        if not absolute:
            mean_sep = (np.prod(self.attrs['BoxSize'])
                        / source.csize) ** (1. / 3)
            linking_length = linking_length * mean_sep
        self._ll = float(linking_length)

        self.labels = self.run()

    def run(self):
        from ..parallel.runtime import mesh_size
        BoxSize = self.attrs['BoxSize']
        nproc = mesh_size(self.comm)
        slab_ok = nproc > 1 and self._ll <= BoxSize[0] / nproc
        if slab_ok:
            return self._run_distributed()

        pos = as_numpy(self._source['Position'])
        roots = _fof_labels(pos, BoxSize, self._ll,
                            periodic=self.attrs['periodic'])

        # compact + size-ordered halo labels (reference _assign_labels)
        roots_np = as_numpy(roots)
        uniq, inv, counts = np.unique(roots_np, return_inverse=True,
                                      return_counts=True)
        nmin = self.attrs['nmin']
        # order by descending count among groups >= nmin
        eligible = counts >= nmin
        order = np.argsort(-counts[eligible], kind='stable')
        label_map = np.zeros(len(uniq), dtype='i8')
        label_map[np.flatnonzero(eligible)[order]] = \
            np.arange(1, eligible.sum() + 1)
        labels = label_map[inv]
        self._halo_count = int(eligible.sum())
        return jnp.asarray(labels)

    def _run_distributed(self):
        """Device-mesh FOF: labels stay sharded end to end; only per-
        group counts (int32, for the size-ordered relabeling the
        reference does with mpsort, fof.py:197-287) touch the host."""
        from ..parallel.domain import (scatter_reduce_by_index,
                                       gather_by_index)
        from ..parallel.runtime import shard_leading
        mesh = self.comm
        pos = jnp.asarray(self._source['Position'])
        N = int(pos.shape[0])
        roots = _fof_labels_distributed(
            pos, self.attrs['BoxSize'], self._ll, mesh,
            periodic=self.attrs['periodic'])

        ones = shard_leading(mesh, jnp.ones(N, jnp.int32))
        counts = scatter_reduce_by_index(roots, ones, N, mesh, op='add')
        counts_np = np.asarray(counts)
        nmin = self.attrs['nmin']
        idx_e = np.flatnonzero(counts_np >= nmin)
        order = np.argsort(-counts_np[idx_e], kind='stable')
        label_map = np.zeros(counts_np.shape[0], dtype='i4')
        label_map[idx_e[order]] = np.arange(1, len(idx_e) + 1,
                                            dtype='i4')
        lmap = shard_leading(mesh, jnp.asarray(label_map))
        labels = gather_by_index(roots, lmap, mesh)
        self._halo_count = int(len(idx_e))
        return labels

    def find_features(self, peakcolumn=None):
        """The halo catalog as a BinnedStatistic-free ArrayCatalog with
        Length / CMPosition / CMVelocity (+ peak position when
        ``peakcolumn`` given); reference fof_catalog (fof.py:427-533)."""
        from ..source.catalog.array import ArrayCatalog
        data = fof_catalog(self._source, self.labels,
                           self._halo_count + 1,
                           self.attrs['BoxSize'],
                           periodic=self.attrs['periodic'],
                           peakcolumn=peakcolumn)
        cat = ArrayCatalog(data, comm=self.comm, **self.attrs)
        return cat

    def to_halos(self, particle_mass, cosmo, redshift, mdef='vir'):
        """A HaloCatalog with Position/Velocity/Mass (reference
        fof.py:130)."""
        from ..source.catalog.halos import HaloCatalog
        features = self.find_features()
        # drop label 0 (unbound particles)
        sel = np.arange(1, len(features))
        data = {
            'Position': features['CMPosition'][1:],
            'Velocity': features['CMVelocity'][1:],
            'Length': features['Length'][1:],
        }
        from ..source.catalog.array import ArrayCatalog
        attrs = dict(self.attrs)
        attrs.update(particle_mass=particle_mass, redshift=redshift,
                     mdef=mdef)
        cat = ArrayCatalog(data, comm=self.comm, **attrs)
        return HaloCatalog(cat, cosmo=cosmo, redshift=redshift,
                           mdef=mdef, mass='Mass', position='Position',
                           velocity='Velocity',
                           particle_mass=particle_mass)


def fof_catalog(source, labels, nhalo, BoxSize, periodic=True,
                peakcolumn=None):
    """Per-halo reductions: Length, periodic center-of-mass position,
    mean velocity (reference fof_catalog/centerofmass,
    fof.py:427-727)."""
    labels = jnp.asarray(labels)
    pos = jnp.asarray(source['Position'])
    box = jnp.asarray(BoxSize, pos.dtype)

    length = jnp.bincount(labels, length=nhalo)

    # periodic center of mass: average offsets relative to a reference
    # particle per halo (the reference uses the same relative-unwrap
    # trick, fof.py:589-643)
    first_idx = jnp.zeros(nhalo, dtype=jnp.int32).at[labels[::-1]].set(
        jnp.arange(len(labels) - 1, -1, -1, dtype=jnp.int32))
    ref = pos[first_idx][labels]
    d = pos - ref
    if periodic:
        d = d - jnp.round(d / box) * box
    dsum = jnp.zeros((nhalo, 3), pos.dtype).at[labels].add(d)
    lsafe = jnp.maximum(length, 1).astype(pos.dtype)[:, None]
    cm = pos[first_idx] + dsum / lsafe
    if periodic:
        cm = jnp.mod(cm, box)

    data = {
        'Length': length,
        'CMPosition': cm,
    }

    if 'Velocity' in source:
        vel = jnp.asarray(source['Velocity'])
        vsum = jnp.zeros((nhalo, 3), vel.dtype).at[labels].add(vel)
        data['CMVelocity'] = vsum / lsafe
    else:
        data['CMVelocity'] = jnp.zeros((nhalo, 3), pos.dtype)

    if peakcolumn is not None and peakcolumn in source:
        density = jnp.asarray(source[peakcolumn])
        # argmax per halo via segment max on (density, index) pairs
        neg = jnp.full(nhalo, -jnp.inf, dtype=density.dtype)
        dmax = neg.at[labels].max(density)
        ispeak = density >= dmax[labels]
        # peak particle per halo; non-peak particles scatter into a
        # spare bucket (nhalo) so they cannot corrupt a real halo
        peak_idx = jnp.zeros(nhalo + 1, jnp.int32).at[
            jnp.where(ispeak, labels, nhalo)].max(
            jnp.arange(len(labels), dtype=jnp.int32))[:nhalo]
        data['PeakPosition'] = pos[peak_idx]
        if 'Velocity' in source:
            data['PeakVelocity'] = jnp.asarray(
                source['Velocity'])[peak_idx]

    return {k: as_numpy(v) for k, v in data.items()}
