"""SubVolumesCatalog: a catalog re-ordered into spatial subvolumes.

Reference: ``nbodykit/source/catalog/subvolumes.py:6`` — a domain-
decomposed copy of a catalog (there via pmesh.domain). Here the
equivalent operation is sorting particles by their slab/subvolume index
so each device's shard holds a contiguous spatial region.
"""

import numpy as np
import jax.numpy as jnp

from ...base.catalog import CatalogSource
from .array import ArrayCatalog


class SubVolumesCatalog(ArrayCatalog):
    """A catalog sorted into a (nx, ny, nz) grid of subvolumes.

    Adds a ``SubVolumeIndex`` column with the flat subvolume id.
    """

    def __init__(self, source, domain=None, position='Position',
                 columns=None):
        if domain is None:
            domain = [1, 1, 1]
        domain = np.asarray(domain, dtype='i8')
        # flat ids below are int32 on-device; guard at trace time
        # before a huge grid wraps silently (nbkl NBK704)
        if int(np.prod(domain)) - 1 > np.iinfo(np.int32).max:
            raise ValueError('subvolume grid %s overflows int32 flat '
                             'indexing' % (tuple(domain),))
        box = np.ones(3) * np.asarray(source.attrs['BoxSize'])
        pos = jnp.asarray(source[position])
        cell = box / domain
        idx = jnp.clip((pos / jnp.asarray(cell)).astype(jnp.int32), 0,
                       jnp.asarray(domain - 1, jnp.int32))
        flat = (idx[:, 0] * domain[1] + idx[:, 1]) * domain[2] \
            + idx[:, 2]
        order = jnp.argsort(flat)
        cols = columns or source.columns
        data = {c: source[c][order] for c in cols}
        data['SubVolumeIndex'] = flat[order]
        ArrayCatalog.__init__(self, data, comm=source.comm,
                              **source.attrs)
        self.attrs['domain'] = domain
