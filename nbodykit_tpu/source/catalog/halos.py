"""HaloCatalog: halos with physically meaningful derived columns.

Reference: ``nbodykit/source/catalog/halos.py:9`` (there bridged to
halotools). Here the derived quantities are computed analytically:
virial mass/radius from the spherical-collapse mean overdensity and the
Dutton & Maccio 2014 concentration-mass relation (the same quantities
the reference exposes via transform.py:376-487).
"""

import numpy as np
import jax.numpy as jnp

from ...base.catalog import CatalogSource, column

RHO_CRIT = 2.7754e11  # (M_sun/h) / (Mpc/h)^3


def halo_mass_definition(mdef, cosmo, redshift):
    """The mean overdensity threshold for a mass definition: 'vir'
    (Bryan & Norman 1998), '200m', '500c', ... ``redshift`` may be a
    scalar or a per-object array (the reference passes arrays through
    halotools; tests/test_transform.py:145)."""
    om = np.asarray(cosmo.Omega_m(np.asarray(redshift)))
    e2 = np.asarray(cosmo.efunc(np.asarray(redshift))) ** 2
    if mdef == 'vir':
        x = om - 1.0
        delta = 18 * np.pi ** 2 + 82 * x - 39 * x ** 2
        return delta * RHO_CRIT * e2
    mult = float(mdef[:-1])
    kind = mdef[-1]
    if kind == 'm':
        return mult * RHO_CRIT * om * e2
    if kind == 'c':
        return mult * RHO_CRIT * e2
    raise ValueError("unknown mass definition %r" % mdef)


class HaloCatalog(CatalogSource):
    """Halos built from a table of (Position, Velocity, Length or Mass).

    Parameters
    ----------
    source : CatalogSource with halo columns
    cosmo : Cosmology; redshift : float; mdef : mass definition
    particle_mass : mass per particle, to convert Length -> Mass
    """

    def __init__(self, source, cosmo, redshift, mdef='vir',
                 mass='Mass', position='Position', velocity='Velocity',
                 particle_mass=None):
        CatalogSource.__init__(self, source.csize, comm=source.comm)
        self._src = source
        self.cosmo = cosmo
        self.attrs.update(source.attrs)
        self.attrs.update(redshift=redshift, mdef=mdef)
        if particle_mass is not None:
            self.attrs['particle_mass'] = particle_mass
        self._names = dict(mass=mass, position=position,
                           velocity=velocity)

    @column
    def Position(self):
        return jnp.asarray(self._src[self._names['position']])

    @column
    def Velocity(self):
        return jnp.asarray(self._src[self._names['velocity']])

    @column
    def Mass(self):
        if self._names['mass'] in self._src:
            return jnp.asarray(self._src[self._names['mass']])
        if 'Length' in self._src and 'particle_mass' in self.attrs:
            return (jnp.asarray(self._src['Length'])
                    * self.attrs['particle_mass'])
        raise ValueError("cannot derive halo masses: need a mass "
                         "column or Length + particle_mass")

    @column
    def Radius(self):
        """The spherical-overdensity radius for attrs['mdef'],
        (3 M / (4 pi Delta rho))^(1/3)."""
        rho = halo_mass_definition(self.attrs['mdef'], self.cosmo,
                                   self.attrs['redshift'])
        M = self['Mass']
        return (3.0 * M / (4 * np.pi * rho)) ** (1.0 / 3)

    @column
    def Concentration(self):
        """Dutton & Maccio 2014 c(M, z) for NFW profiles (capability
        analog of reference transform.HaloConcentration)."""
        z = self.attrs['redshift']
        M = self['Mass']
        b = -0.097 + 0.024 * z
        a = 0.537 + (1.025 - 0.537) * np.exp(-0.718 * z ** 1.08)
        logc = a + b * jnp.log10(M / 1e12)
        return 10.0 ** logc

    @column
    def VelocityOffset(self):
        """Velocity in units of the RSD position offset."""
        z = self.attrs['redshift']
        E = float(self.cosmo.efunc(z))
        return self['Velocity'] * ((1.0 + z) / (100.0 * E))

    def populate(self, model=None, seed=None, **params):
        """Populate the halos with galaxies under an HOD model
        (reference: source/catalog/halos.py:202-270 via halotools;
        here nbodykit_tpu.hod natively)."""
        from ...hod import HODModel, Zheng07Model
        if model is None:
            model = Zheng07Model(**params)
        elif isinstance(model, type):
            # an occupation CLASS (e.g. populate(Zheng07Model,
            # logMmin=...)): instantiate it with the HOD parameters
            model = model(**params)
        elif params:
            raise ValueError(
                "HOD parameters can only be passed with an occupation "
                "class (got an instance of %s)" % type(model).__name__)
        if not isinstance(model, HODModel):
            model = HODModel(model, seed=seed)
        return model.populate(self, seed=seed)

    def to_mesh(self, *args, **kwargs):
        return CatalogSource.to_mesh(self, *args, **kwargs)


# reference-path re-export: the reference defines PopulatedHaloCatalog
# in this module (source/catalog/halos.py); the class itself lives with
# the HOD machinery to avoid an import cycle
from ...hod import PopulatedHaloCatalog  # noqa: F401,E402
