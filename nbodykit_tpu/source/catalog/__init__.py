from .array import ArrayCatalog
from .uniform import RandomCatalog, UniformCatalog
from .lognormal import LogNormalCatalog

__all__ = ['ArrayCatalog', 'RandomCatalog', 'UniformCatalog',
           'LogNormalCatalog']
