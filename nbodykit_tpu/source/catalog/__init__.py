from .array import ArrayCatalog
from .uniform import RandomCatalog, UniformCatalog

__all__ = ['ArrayCatalog', 'RandomCatalog', 'UniformCatalog']
