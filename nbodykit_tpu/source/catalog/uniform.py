"""Random and uniform catalogs (reference:
nbodykit/source/catalog/uniform.py:6,62)."""

import numpy as np

from ...base.catalog import CatalogSource, column
from ...rng import DistributedRNG


class RandomCatalog(CatalogSource):
    """A catalog whose columns are drawn from a device-count-invariant
    random generator exposed as :attr:`rng`."""

    def __init__(self, csize, seed=None, comm=None):
        if seed is None:
            seed = np.random.randint(0, 2 ** 31 - 1)
        if csize == 0:
            raise ValueError("no random particles generated!")
        CatalogSource.__init__(self, csize, comm=comm)
        self.attrs['seed'] = seed
        self._rng = DistributedRNG(seed, csize, comm=self.comm)

    @property
    def rng(self):
        return self._rng

    def __repr__(self):
        return "RandomCatalog(size=%d, seed=%s)" % (
            self.size, self.attrs['seed'])


class UniformCatalog(RandomCatalog):
    """Uniformly distributed ``Position`` and ``Velocity`` in a box; the
    total count is Poisson(nbar * volume) drawn from ``seed``."""

    def __init__(self, nbar, BoxSize, seed=None, dtype='f8', comm=None):
        _BoxSize = np.empty(3, dtype='f8')
        _BoxSize[:] = BoxSize

        if seed is None:
            seed = np.random.randint(0, 2 ** 31 - 1)
        N = int(np.random.RandomState(seed).poisson(
            nbar * np.prod(_BoxSize)))
        if N == 0:
            raise ValueError("no uniform particles generated; "
                             "increase nbar")
        RandomCatalog.__init__(self, N, seed=seed, comm=comm)
        self.attrs['BoxSize'] = _BoxSize
        self.attrs['nbar'] = nbar

        from ...utils import working_dtype
        wdt = working_dtype(dtype)
        box = np.asarray(_BoxSize)
        self._pos = (self.rng.uniform(itemshape=(3,), dtype=wdt) * box
                     ).astype(wdt)
        self._vel = (self.rng.uniform(itemshape=(3,), dtype=wdt) * box
                     * 0.01).astype(wdt)

    def __repr__(self):
        return "UniformCatalog(size=%d, seed=%s)" % (
            self.size, self.attrs['seed'])

    @column
    def Position(self):
        """Uniform positions in [0, BoxSize)."""
        return self._pos

    @column
    def Velocity(self):
        """Uniform velocities in [0, 0.01*BoxSize)."""
        return self._vel
