"""LogNormalCatalog: lognormal + Zel'dovich mock galaxy catalog.

Reference: ``nbodykit/source/catalog/lognormal.py:9`` (`_makesource`
:137-190): Gaussian delta and displacement fields from a linear power
spectrum, lognormal transform with bias, Poisson sampling, Zel'dovich
position update, linear velocities v = f a H psi.
"""

import numpy as np
import jax.numpy as jnp

from ...base.catalog import CatalogSource, column
from ...pmesh import ParticleMesh
from ... import mockmaker


class LogNormalCatalog(CatalogSource):
    """Poisson-sampled lognormal realization of a linear power spectrum,
    with Zel'dovich displacements and velocities.

    Parameters
    ----------
    Plin : callable P(k); if it carries ``cosmo``/``redshift``
        attributes (like LinearPower), they set the growth rate for
        velocities
    nbar : mean number density, in (box units)^-3
    BoxSize, Nmesh : mesh geometry
    bias : lognormal bias b (delta_g = exp(b delta) - 1)
    seed : realization seed (device-count invariant)
    cosmo, redshift : override Plin's attributes
    """

    def __init__(self, Plin, nbar, BoxSize, Nmesh, bias=2.0, seed=None,
                 cosmo=None, redshift=None, unitary_amplitude=False,
                 inverted_phase=False, dtype='f4', comm=None):
        if seed is None:
            seed = np.random.randint(0, 2 ** 31 - 1)

        cosmo = cosmo if cosmo is not None else getattr(Plin, 'cosmo', None)
        redshift = redshift if redshift is not None else \
            getattr(Plin, 'redshift', None)

        self._pm = ParticleMesh(Nmesh, BoxSize, dtype=dtype, comm=comm)
        pm = self._pm

        delta, disp = mockmaker.gaussian_real_fields(
            pm, Plin, seed, unitary_amplitude=unitary_amplitude,
            inverted_phase=inverted_phase, compute_displacement=True)

        pos, psi = mockmaker.poisson_sample_to_points(
            delta, disp, pm, nbar, bias=bias, seed=seed)

        # Zel'dovich update: x -> x + psi (periodic wrap)
        box = jnp.asarray(pm.BoxSize, pos.dtype)
        pos = jnp.mod(pos + psi, box)

        # velocities: v = f * a * H(a) * psi = f * 100 * E(z) / (1+z) psi
        if cosmo is not None and redshift is not None:
            f = float(cosmo.scale_independent_growth_rate(redshift))
            E = float(cosmo.efunc(redshift))
            vfac = f * 100.0 * E / (1.0 + redshift)
        else:
            f = 0.0
            vfac = 0.0

        CatalogSource.__init__(self, pos.shape[0], comm=comm)
        self.attrs['BoxSize'] = pm.BoxSize.copy()
        self.attrs['Nmesh'] = pm.Nmesh.copy()
        self.attrs.update(nbar=nbar, bias=bias, seed=seed)
        if redshift is not None:
            self.attrs['redshift'] = redshift
        if hasattr(Plin, 'attrs'):
            self.attrs.update({k: v for k, v in Plin.attrs.items()
                               if k not in self.attrs})

        self._pos = pos
        self._vel = (psi * vfac).astype(pos.dtype)
        self._voff = (psi * f).astype(pos.dtype)  # f * psi, Mpc/h
        self._cosmo = cosmo

    @column
    def Position(self):
        return self._pos

    @column
    def Velocity(self):
        return self._vel

    @column
    def VelocityOffset(self):
        """RSD position offset f * psi in Mpc/h, so that
        x_rsd = x + VelocityOffset . los (reference convention,
        lognormal.py:189)."""
        return self._voff

    def __repr__(self):
        return "LogNormalCatalog(size=%d, seed=%s)" % (
            self.size, self.attrs['seed'])
