"""MultipleSpeciesCatalog: several catalogs under one namespace.

Reference: ``nbodykit/source/catalog/species.py:9``. Columns are
addressed as ``"<species>/<column>"``; ``cat[species]`` returns the
underlying catalog (a view, so column assignment propagates).
"""

import numpy as np
import jax.numpy as jnp

from ...base.catalog import CatalogSourceBase


class MultipleSpeciesCatalog(CatalogSourceBase):
    """A container of named CatalogSource species.

    Parameters
    ----------
    names : list of str — species names (no '/' allowed)
    *species : the catalogs, same length as names
    """

    def __init__(self, names, *species, **kwargs):
        if len(set(names)) != len(names):
            raise ValueError("species names must be unique")
        if len(names) != len(species):
            raise ValueError("need one name per species catalog")
        if any('/' in name for name in names):
            raise ValueError("species names cannot contain '/'")

        CatalogSourceBase.__init__(self, comm=species[0].comm)
        self.attrs['species'] = list(names)
        self._species = dict(zip(names, species))

        # species attrs are namespaced into the container attrs
        for name, cat in self._species.items():
            for k, v in cat.attrs.items():
                self.attrs['%s.%s' % (name, k)] = v

    @property
    def species(self):
        return self.attrs['species']

    @property
    def columns(self):
        out = []
        for name in self.species:
            out += ['%s/%s' % (name, col)
                    for col in self._species[name].columns]
        return sorted(out)

    def __len__(self):
        return sum(len(self._species[name]) for name in self.species)

    @property
    def csize(self):
        return len(self)

    def __getitem__(self, key):
        if isinstance(key, str):
            if key in self.species:
                return self._species[key]
            if '/' in key:
                name, col = key.split('/', 1)
                if name not in self.species:
                    raise KeyError("no species named %r" % name)
                return self._species[name][col]
        raise KeyError("column spec %r; use 'species/column' or a "
                       "species name" % (key,))

    def __setitem__(self, key, value):
        if '/' not in key:
            raise ValueError("set columns as 'species/column'")
        name, col = key.split('/', 1)
        self._species[name][col] = value

    def to_mesh(self, Nmesh=None, BoxSize=None, dtype='f4',
                interlaced=False, compensated=False, resampler='cic',
                position='Position', weight='Weight', value='Value',
                selection='Selection'):
        from ..mesh.species import MultipleSpeciesCatalogMesh
        if Nmesh is None:
            Nmesh = self.attrs.get('Nmesh', None)
        if BoxSize is None:
            BoxSize = self.attrs.get('BoxSize', None)
        if Nmesh is None or BoxSize is None:
            raise ValueError("pass Nmesh and BoxSize to to_mesh")
        return MultipleSpeciesCatalogMesh(
            self, Nmesh=Nmesh, BoxSize=BoxSize, dtype=dtype,
            interlaced=interlaced, compensated=compensated,
            resampler=resampler, position=position, weight=weight,
            value=value, selection=selection)
