"""File-backed catalogs: one class per file format, via a factory.

Reference: ``nbodykit/source/catalog/file.py:15,166`` — FileCatalogBase
wraps a FileType (or FileStack of them) as a CatalogSource; the factory
stamps out CSVCatalog, BinaryCatalog, BigFileCatalog, HDFCatalog,
FITSCatalog, TPMBinaryCatalog, Gadget1Catalog (file.py:232-238).
"""

import numpy as np
import jax.numpy as jnp

from ...base.catalog import CatalogSource, column
from ... import io as _io


class FileCatalogBase(CatalogSource):
    """A CatalogSource whose columns come from a file (stack).

    The whole selection is loaded host-side on first column access and
    promoted to (sharded) device arrays; partitioned streaming reads
    can be added per-column via ``get_hardcolumn``.
    """

    def __init__(self, filetype, args=(), kwargs={}, comm=None):
        path = args[0] if args else kwargs.get('path')
        rest = args[1:]
        if isinstance(path, str) and ('*' in path or '?' in path):
            self._source = _io.FileStack(filetype, path, *rest, **kwargs)
        else:
            try:
                self._source = filetype(*args, **kwargs)
            except (IOError, OSError, FileNotFoundError):
                self._source = _io.FileStack(filetype, path, *rest,
                                             **kwargs)
        CatalogSource.__init__(self, self._source.size, comm=comm)
        self.attrs.update(getattr(self._source, 'attrs', {}))

    @property
    def hardcolumns(self):
        base = CatalogSource.hardcolumns.fget(self)
        return sorted(set(base) | set(self._source.columns))

    def __getitem__(self, sel):
        if isinstance(sel, str) and sel not in self._columns and \
                sel not in self._cache and sel in self._source.columns:
            data = self._source.read([sel], 0, self._source.size)[sel]
            val = self._promote(jnp.asarray(np.ascontiguousarray(data)))
            self._cache[sel] = val
            return val
        return CatalogSource.__getitem__(self, sel)


def _make_file_catalog(name, filetype, doc_fmt):
    def __init__(self, *args, comm=None, **kwargs):
        FileCatalogBase.__init__(self, filetype, args=args,
                                 kwargs=kwargs, comm=comm)
    cls = type(name, (FileCatalogBase,), {'__init__': __init__})
    cls.__doc__ = ("CatalogSource of a %s (reference factory: "
                   "nbodykit/source/catalog/file.py:232-238). Accepts "
                   "glob patterns for multi-file datasets." % doc_fmt)
    return cls


CSVCatalog = _make_file_catalog('CSVCatalog', _io.CSVFile,
                                'delimited text file')
BinaryCatalog = _make_file_catalog('BinaryCatalog', _io.BinaryFile,
                                   'column-appended binary file')
BigFileCatalog = _make_file_catalog('BigFileCatalog', _io.BigFile,
                                    'bigfile column store')
HDFCatalog = _make_file_catalog('HDFCatalog', _io.HDFFile, 'HDF5 file')
FITSCatalog = _make_file_catalog('FITSCatalog', _io.FITSFile,
                                 'FITS binary table')
TPMBinaryCatalog = _make_file_catalog('TPMBinaryCatalog',
                                      _io.TPMBinaryFile, 'TPM snapshot')
Gadget1Catalog = _make_file_catalog('Gadget1Catalog', _io.Gadget1File,
                                    'Gadget-1 snapshot')


class FileCatalog(FileCatalogBase):
    """Generic file catalog taking the FileType class as its first
    argument (reference: nbodykit/source/catalog/file.py:202-231):
    ``FileCatalog(filetype, path, ...)``."""

    def __init__(self, filetype, path, *args, comm=None, attrs=None,
                 **kwargs):
        FileCatalogBase.__init__(self, filetype, args=(path,) + args,
                                 kwargs=kwargs, comm=comm)
        self.attrs.update(attrs or {})


def FileCatalogFactory(name, filetype, examples=None):
    """Create a CatalogSource class reading a custom
    :class:`~nbodykit_tpu.io.base.FileType` subclass (reference
    factory: nbodykit/source/catalog/file.py:232-238). ``examples`` is
    accepted for signature parity and ignored."""
    return _make_file_catalog(
        name, filetype, getattr(filetype, '__name__', 'file'))
