"""ArrayCatalog: wrap in-memory columns as a CatalogSource
(reference: nbodykit/source/catalog/array.py:7)."""

import numpy as np
import jax.numpy as jnp

from ...base.catalog import CatalogSource


class ArrayCatalog(CatalogSource):
    """A catalog built from a dict of arrays or a structured numpy array.

    Parameters
    ----------
    data : dict of (name -> array) or structured numpy array; all
        leading dimensions must agree
    **kwargs : stored in :attr:`attrs`
    """

    def __init__(self, data, comm=None, **kwargs):
        if isinstance(data, np.ndarray) and data.dtype.names is not None:
            data = {name: data[name] for name in data.dtype.names}
        if not isinstance(data, dict):
            raise TypeError("data must be a dict of arrays or a "
                            "structured numpy array")
        sizes = {k: np.shape(v)[0] for k, v in data.items()}
        if len(set(sizes.values())) > 1:
            raise ValueError("column length mismatch: %s" % sizes)
        size = next(iter(sizes.values())) if sizes else 0

        CatalogSource.__init__(self, size, comm=comm)
        self.attrs.update(kwargs)
        for name, value in data.items():
            self[name] = jnp.asarray(value)
