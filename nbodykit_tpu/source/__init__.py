"""Concrete catalog and mesh sources (SURVEY.md §2 'Catalog sources' /
'Mesh sources')."""
