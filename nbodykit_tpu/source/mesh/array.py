"""ArrayMesh: wrap a host numpy array as a distributed MeshSource
(reference: nbodykit/source/mesh/array.py:8, which scatters from the
root rank; here device_put with a slab sharding does the same job)."""

import numpy as np
import jax.numpy as jnp

from ...base.mesh import MeshSource, Field
from ...parallel.runtime import shard_leading


class ArrayMesh(MeshSource):
    """A MeshSource from a concrete (Nmesh, Nmesh, Nmesh) numpy array."""

    def __init__(self, array, BoxSize, comm=None, **kwargs):
        array = np.asarray(array)
        if array.ndim != 3:
            raise ValueError("ArrayMesh expects a 3-D array")
        MeshSource.__init__(self, array.shape, BoxSize,
                            dtype=array.dtype.str, comm=comm)
        self.attrs.update(kwargs)
        value = jnp.asarray(array)
        if self.comm is not None:
            value = shard_leading(self.comm, value)
        self._value = value

    def to_real_field(self):
        return Field(self._value, self.pm, 'real')
