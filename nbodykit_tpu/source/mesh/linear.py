"""LinearMesh: a Gaussian realization of a linear power spectrum
(reference: nbodykit/source/mesh/linear.py:6)."""

import numpy as np
import jax.numpy as jnp

from ...base.mesh import MeshSource, Field


class LinearMesh(MeshSource):
    """Gaussian field with a given power spectrum.

    Parameters
    ----------
    Plin : callable P(k) -> power, in the box units
    BoxSize, Nmesh : geometry
    seed : int — realization seed (device-count invariant)
    unitary_amplitude : bool — fix |delta_k| to its rms
    inverted_phase : bool — flip the phase
    """

    def __init__(self, Plin, BoxSize, Nmesh, seed=None,
                 unitary_amplitude=False, inverted_phase=False,
                 dtype='f4', comm=None):
        self.Plin = Plin
        MeshSource.__init__(self, Nmesh, BoxSize, dtype=dtype, comm=comm)
        if seed is None:
            seed = np.random.randint(0, 2 ** 31 - 1)
        self.attrs['seed'] = seed
        self.attrs['unitary_amplitude'] = unitary_amplitude
        self.attrs['inverted_phase'] = inverted_phase
        if hasattr(Plin, 'attrs'):
            self.attrs.update(Plin.attrs)

    def to_complex_field(self):
        """delta_k = whitenoise * sqrt(P(k) / V), zero DC (reference
        recipe: mockmaker.py:7-141)."""
        pm = self.pm
        eta = pm.generate_whitenoise(
            self.attrs['seed'],
            unitary=self.attrs['unitary_amplitude'],
            inverted_phase=self.attrs['inverted_phase'])
        kx, ky, kz = pm.k_list(dtype=jnp.float64
                               if pm.dtype.itemsize > 4 else jnp.float32)
        k2 = kx ** 2 + ky ** 2 + kz ** 2
        kmag = jnp.sqrt(k2)
        V = float(np.prod(pm.BoxSize))
        power = jnp.asarray(self.Plin(kmag))
        amp = jnp.sqrt(jnp.where(power > 0, power, 0.0) / V)
        delta_k = eta * amp.astype(eta.real.dtype)
        delta_k = jnp.where(k2 == 0, 0.0, delta_k)
        return Field(delta_k, pm, 'complex')
