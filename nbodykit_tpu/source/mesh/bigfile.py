"""BigFileMesh: load a saved mesh field.

Reference: ``nbodykit/source/mesh/bigfile.py:15`` — reads a field
written by ``MeshSource.save`` back as a MeshSource (the de-facto
checkpoint format for intermediate fields, SURVEY.md §5).
"""

import numpy as np
import jax.numpy as jnp

from ...base.mesh import MeshSource, Field
from ...io.bigfile import BigFileDataset, read_attrs_file

from ...parallel.runtime import shard_leading, mesh_size

import os


class BigFileMesh(MeshSource):
    """A MeshSource backed by a saved field directory."""

    def __init__(self, path, dataset='Field', comm=None):
        self.path = path
        self.dataset = dataset
        attrs = read_attrs_file(os.path.join(path, dataset))
        if 'ndarray.shape' not in attrs:
            raise ValueError("%s does not look like a saved mesh "
                             "(missing ndarray.shape)" % path)
        shape = tuple(int(n) for n in np.atleast_1d(
            attrs['ndarray.shape']))
        Nmesh = attrs.get('Nmesh', shape)
        BoxSize = attrs.get('BoxSize', 1.0)

        self._block = BigFileDataset(path, dataset)
        self._shape = shape
        self.attrs = {k: v for k, v in attrs.items()
                      if k != 'ndarray.shape'}
        MeshSource.__init__(self, Nmesh, BoxSize,
                            dtype=self._block.dtype.str, comm=comm)

    def to_real_field(self):
        data = self._block.read(0, self._block.size)
        value = jnp.asarray(data.reshape(self._shape))
        if self.comm is not None and mesh_size(self.comm) > 1:
            value = shard_leading(self.comm, value)
        return Field(value, self.pm, 'real')
