from .catalog import CatalogMesh
from .linear import LinearMesh
from .array import ArrayMesh
from ...base.mesh import FieldMesh

__all__ = ['CatalogMesh', 'LinearMesh', 'ArrayMesh', 'FieldMesh']
