"""MultipleSpeciesCatalogMesh: paint the sum of several species.

Reference: ``nbodykit/source/mesh/species.py:11`` — paints each species
with its own weights onto the same mesh and sums; normalization is the
combined 1+delta.
"""

import numpy as np
import jax.numpy as jnp

from ...base.mesh import MeshSource, Field
from .catalog import CatalogMesh


class MultipleSpeciesCatalogMesh(MeshSource):
    """Mesh view of a MultipleSpeciesCatalog; ``mesh[species]`` gives
    the single-species CatalogMesh."""

    def __init__(self, source, Nmesh, BoxSize, dtype='f4',
                 interlaced=False, compensated=False, resampler='cic',
                 position='Position', weight='Weight', value='Value',
                 selection='Selection'):
        self.source = source
        attrs = dict(source.attrs)
        attrs.update(getattr(self, 'attrs', {}))  # subclass pre-set wins
        self.attrs = attrs
        MeshSource.__init__(self, Nmesh, BoxSize, dtype=dtype,
                            comm=source.comm)
        self.interlaced = interlaced
        self.compensated = compensated
        self.resampler = resampler
        self.position = position
        self.weight = weight
        self.value = value
        self.selection = selection

    def __getitem__(self, species):
        if species not in self.source.species:
            raise KeyError("species %r not in %s" % (species,
                                                     self.source.species))
        cat = self.source[species]
        return CatalogMesh(
            cat, Nmesh=self.attrs['Nmesh'], BoxSize=self.attrs['BoxSize'],
            dtype=self.pm.dtype.str, interlaced=self.interlaced,
            compensated=self.compensated, resampler=self.resampler,
            position=self.position, weight=self.weight, value=self.value,
            selection=self.selection)

    def to_real_field(self):
        """Sum of the unnormalized species paints, normalized by the
        total weighted number per cell (combined 1+delta; reference
        source/mesh/species.py)."""
        total = None
        attrs = {}
        Wsum = 0.0
        Nsum = 0.0
        for name in self.source.species:
            f = self[name].to_real_field(normalize=False)
            for k, v in f.attrs.items():
                attrs['%s.%s' % (name, k)] = v
            Wsum += f.attrs['W']
            Nsum += f.attrs['N']
            total = f.value if total is None else total + f.value
        nbar = Wsum / self.pm.Ntot
        if nbar > 0:
            total = total / nbar
        attrs['N'] = Nsum
        attrs['W'] = Wsum
        attrs['num_per_cell'] = nbar
        return Field(total, self.pm, 'real', attrs)
