"""CatalogMesh: paint a catalog onto a density mesh.

Reference: ``nbodykit/source/mesh/catalog.py:11``. Capability surface:
window interpolation (nnb/cic/tsc/pcs), selection/weight/value columns,
interlacing (two half-cell-shifted meshes combined in k-space), window
compensation as a deferred complex-space action, and the 1+delta
normalization with N/W/W2/shotnoise attrs (to_real_field :155-403).

TPU redesign: no chunk/backoff loop — the whole paint (exchange +
scatter + halo) is one XLA program; the particle-count invariants
(N, W, W2) are plain global reductions.
"""

import numpy as np
import jax.numpy as jnp

from ...base.mesh import MeshSource, Field
from ...ops.window import compensation_transfer, window_support


class CatalogMesh(MeshSource):
    """A MeshSource that paints ``source``'s particles when computed.

    Parameters
    ----------
    source : CatalogSource
    Nmesh, BoxSize, dtype : mesh geometry
    resampler : window name ('nnb'|'cic'|'tsc'|'pcs')
    interlaced : bool — two-pass interlaced painting (aliasing
        suppression)
    compensated : bool — queue the Fourier-space window compensation
    position, weight, value, selection : column names
    """

    def __init__(self, source, Nmesh, BoxSize, dtype='f4', resampler='cic',
                 interlaced=False, compensated=False, position='Position',
                 weight='Weight', value='Value', selection='Selection'):
        window_support(resampler)  # validate early
        self.source = source
        self.attrs = dict(source.attrs)
        MeshSource.__init__(self, Nmesh, BoxSize, dtype=dtype,
                            comm=source.comm)
        self.resampler = resampler
        self.interlaced = interlaced
        self.compensated = compensated
        self.position = position
        self.weight = weight
        self.value = value
        self.selection = selection
        self.attrs.update(interlaced=interlaced, compensated=compensated,
                          resampler=resampler)

    @property
    def actions(self):
        actions = self._actions
        if self.compensated:
            actions = self._compensation_actions() + actions
        return actions

    def _compensation_actions(self):
        transfer = compensation_transfer(self.resampler, self.interlaced)
        return [('complex', transfer, 'circular')]

    def to_real_field(self, normalize=True):
        """Paint and normalize to 1 + delta; attrs gain N, W, W2,
        shotnoise, num_per_cell (reference semantics,
        source/mesh/catalog.py:155-403)."""
        pm = self.pm
        src = self.source

        pos = src[self.position]
        weight = src[self.weight] if self.weight in src else None
        value = src[self.value] if self.value in src else None
        sel = src[self.selection] if self.selection in src else None

        if weight is None:
            weight = jnp.ones(pos.shape[0])
        if value is None:
            value = jnp.ones(pos.shape[0])
        if sel is not None:
            # masked-out particles paint with zero mass (static shapes —
            # no boolean compress under a device mesh)
            weight = jnp.where(sel, weight, 0.0)

        mass = (weight * value).astype(pm.dtype)

        N = jnp.where(sel, 1.0, 0.0).sum() if sel is not None \
            else float(pos.shape[0])
        W = weight.sum()
        W2 = (weight ** 2).sum()

        if not self.interlaced:
            field = pm.paint(pos, mass, resampler=self.resampler)
        else:
            # two meshes offset by half a cell, combined in k-space
            # with the phase that re-centers the shifted one:
            # paint(shift=0.5) deposits at cell coords x/H - 1/2, i.e.
            # samples on the grid x = (j + 1/2) H, so its spectrum
            # carries e^{+ik.H/2} and the combine multiplies e^{-ik.H/2}
            f1 = pm.paint(pos, mass, resampler=self.resampler)
            f2 = pm.paint(pos, mass, resampler=self.resampler, shift=0.5)
            c1 = pm.r2c(f1)
            c2 = pm.r2c(f2)
            kx, ky, kz = pm.k_list()
            H = pm.cellsize
            kH = kx * H[0] + ky * H[1] + kz * H[2]
            combined = 0.5 * (c1 + c2 * jnp.exp(-0.5j * kH))
            field = pm.c2r(combined)

        # to host scalars for attrs (cheap; small reductions)
        N = float(N)
        W = float(W)
        W2 = float(W2)
        nbar = W / pm.Ntot  # mean weighted objects per cell
        shotnoise = float(np.prod(pm.BoxSize)) * W2 / W ** 2 if W > 0 \
            else 0.0

        attrs = dict(N=N, W=W, W2=W2, shotnoise=shotnoise,
                     num_per_cell=nbar)

        if normalize:
            if nbar > 0:
                field = field / nbar
            else:
                import warnings
                warnings.warn("painting an empty catalog; field set to "
                              "uniform", RuntimeWarning)
                field = jnp.ones_like(field)

        return Field(field, pm, 'real', attrs)

    def to_mesh(self):
        return self


# ---------------------------------------------------------------------------
# Named compensation functions — the reference exposes these as public
# apply-style kernels (nbodykit/source/mesh/catalog.py:453-585) that
# users pass to ``mesh.apply(..., kind='circular', mode='complex')`` in
# recipes. Each takes the circular frequencies ``w`` and the complex
# field ``v`` and divides out the window transfer. Reference naming:
# the PLAIN names are the pure Jing 2005 eq.18 sinc^p kernels (what
# get_compensation selects when interlacing already removed aliasing),
# and the *Shotnoise names are the eq.20 first-order
# aliasing-corrected forms (selected when NOT interlaced).

def _named_compensation(name, resampler, pure_sinc):
    func = compensation_transfer(resampler, interlaced=pure_sinc)
    func.__name__ = func.__qualname__ = name
    return func


CompensateCIC = _named_compensation('CompensateCIC', 'cic', True)
CompensateTSC = _named_compensation('CompensateTSC', 'tsc', True)
CompensatePCS = _named_compensation('CompensatePCS', 'pcs', True)
CompensateCICShotnoise = _named_compensation(
    'CompensateCICShotnoise', 'cic', False)
CompensateTSCShotnoise = _named_compensation(
    'CompensateTSCShotnoise', 'tsc', False)
CompensatePCSShotnoise = _named_compensation(
    'CompensatePCSShotnoise', 'pcs', False)
