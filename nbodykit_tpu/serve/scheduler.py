"""Placement + the warm program cache.

Two jobs:

**Programs stay warm.**  Each (program key, worker) pair builds its
compiled analysis program exactly once, wrapped in
:func:`~nbodykit_tpu.diagnostics.instrumented_jit` under a label keyed
by shape class (``serve.fftpower.mesh64-part1e5``), so the
``compile.<label>.misses`` / ``.hits`` counters are the PROOF that the
second identical-shape request compiles nothing.  TUNE_CACHE.json
winners are resolved once per (shape class, device count) — not once
per request — behind a lock, and the resolution is memoized alongside
the program.

**Placement is cache-affine.**  A compiled XLA executable is bound to
the devices it was built for, so the scheduler routes a request to the
sub-mesh worker that already holds its warm program: affinity =
``hash(program_key) % n_workers``.  An idle worker may still steal the
globally best-ranked ticket (paying one compile to warm its own copy)
rather than sit out a backlog — classic cache-aware scheduling with
work stealing.  Ranking within a worker's view is priority (desc),
deadline (asc), submission order (asc).

The device programs themselves live here too: self-contained
(seed -> spectrum) pipelines — uniform realization, paint, r2c,
window compensation, integer-lattice shell binning — one per
algorithm, modeled on bench.py's fused pipeline.  On a 1-device
sub-mesh the program is plain jax ops (no shard_map), which is what
makes it vmap-batchable (:mod:`.batching`); on a multi-device
sub-mesh the same builder produces the shard_map form.
"""

import threading

from ..diagnostics import counter, instrumented_jit
from ..parallel.runtime import mesh_size

BOX_SIZE = 1000.0


def program_label(request):
    """The instrumented-jit label for a request's program: keyed by
    algorithm + shape class, NOT by exact shape — the granularity the
    compile miss/hit counters aggregate at."""
    return 'serve.%s.%s' % (request.algorithm.lower(),
                            request.shape_class)


# ---------------------------------------------------------------------------
# device programs

def _binned_power(pm, c, resampler, npart):
    """Window-compensated, hermitian-weighted |delta_k|^2 binned onto
    integer-lattice k shells (exact shell assignment via the shared
    :func:`~nbodykit_tpu.ops.histogram.lattice_shell_index`).  Returns
    (k, P(k), nmodes) with nmesh//2 shells."""
    import jax.numpy as jnp
    import numpy as np
    from ..ops.histogram import lattice_shell_index
    from ..ops.window import compensation_transfer

    nmesh = int(pm.Nmesh[0])
    L = float(pm.BoxSize[0])
    nbins = nmesh // 2
    V = L ** 3

    w = pm.k_list(dtype=jnp.float32, circular=True)
    c = compensation_transfer(resampler, False)(w, c)
    p3 = (jnp.abs(c) ** 2).astype(jnp.float32) * V
    p3 = p3.at[0, 0, 0].set(0.0)

    ix, iy, iz = pm.i_list_complex()
    shell = lattice_shell_index(ix * ix + iy * iy + iz * iz, nbins)
    wgt = jnp.broadcast_to(pm.hermitian_weights(jnp.float32), p3.shape)
    flat = jnp.broadcast_to(shell, p3.shape).reshape(-1)
    P = jnp.zeros(nbins, jnp.float32).at[flat].add(
        (p3 * wgt).reshape(-1))
    Nm = jnp.zeros(nbins, jnp.float32).at[flat].add(wgt.reshape(-1))
    Nm0 = Nm.at[0].set(jnp.maximum(Nm[0] - 1.0, 0.0))  # drop DC mode
    k = jnp.asarray(np.arange(nbins, dtype='f4')) \
        * jnp.float32(2 * np.pi / L)
    return k, P / jnp.maximum(Nm, 1.0), Nm0


def _delta_c(pm, pos, resampler, npart):
    """Painted overdensity in k space (forward-normalized r2c of
    paint/nbar)."""
    field, _ = pm.paint(pos, 1.0, resampler=resampler,
                        return_dropped=True)
    return pm.r2c(field / (float(npart) / pm.Ntot))


def _uniform_pos(seed, npart, L):
    import jax
    import jax.numpy as jnp
    return jax.random.uniform(jax.random.key(seed), (npart, 3),
                              jnp.float32, 0.0, L)


def _build_data(request, pm):
    """The (painted field -> (k, P, nmodes)) stage of a ``data_ref``
    program.  The paint itself is NOT in here: streaming ingestion is
    eager by construction (chunks arrive over time), so the jitted
    boundary starts at the finished field — one warm executable per
    shape serves every survey."""
    npart = request.npart
    resampler = request.resampler

    def from_field(field):
        c = pm.r2c(field / (float(npart) / pm.Ntot))
        return _binned_power(pm, c, resampler, npart)
    return from_field


def _build_single(request, pm):
    """The single-realization (seed -> (x, y, nmodes)) function for
    one algorithm on one ParticleMesh."""
    import jax.numpy as jnp
    npart = request.npart
    resampler = request.resampler
    L = float(pm.BoxSize[0])

    if request.algorithm == 'FFTPower':
        def single(seed):
            c = _delta_c(pm, _uniform_pos(seed, npart, L), resampler,
                         npart)
            return _binned_power(pm, c, resampler, npart)

    elif request.algorithm == 'ConvolvedFFTPower':
        # FKP-style: data minus an independent synthetic randoms
        # realization (alpha = 1), monopole of the difference field
        def single(seed):
            data = _delta_c(pm, _uniform_pos(seed, npart, L),
                            resampler, npart)
            rand = _delta_c(pm, _uniform_pos(seed + 2 ** 20, npart, L),
                            resampler, npart)
            return _binned_power(pm, data - rand, resampler, npart)

    elif request.algorithm == 'Forward':
        # one field-level-inference sample: realize truth linear modes
        # from the seed, evolve through LPT + KDK PM to an observed
        # density, then take ONE preconditioned gradient step of the
        # Gaussian posterior from the zero initial guess — a full
        # forward+backward pipeline (the reverse-mode pricing branch
        # admission used).  Deliverable: binned P(k) of the recovered
        # linear modes — deterministic in the seed, shadow-verifiable
        # like any seeded request.
        import jax
        from ..forward import ForwardModel, binned_power
        from ..parallel.runtime import use_mesh

        # pin the build context to pm's mesh: on the batchable path pm
        # was built under use_mesh(None) and the model's lattices must
        # stay comm-less (plain ops) for vmap
        with use_mesh(pm.comm):
            model = ForwardModel(request.nmesh, request.npart,
                                 BoxSize=L,
                                 pm_steps=request.pm_steps or 5,
                                 dtype=request.dtype,
                                 resampler=resampler, comm=pm.comm)
        inv_noise = 10.0   # sigma = 0.1 in 1+delta units
        step = 0.05        # one fixed-size gradient step

        def single(seed):
            truth = model.lattice.generate_whitenoise(seed) * model.amp
            obs = model.density(truth)

            def loss(white):
                d = model.density(model.modes_from_white(white))
                r = (d - obs) * inv_noise
                return 0.5 * jnp.sum(r * r) \
                    + 0.5 * jnp.sum(white * white)

            g = jax.grad(loss)(model.white_guess())
            scale = jnp.max(jnp.abs(g))
            white = -step * g / jnp.maximum(scale, 1e-30)
            k, P, nm = binned_power(model.lattice,
                                    model.modes_from_white(white))
            return (k.astype(jnp.float32), P.astype(jnp.float32),
                    nm.astype(jnp.float32))

    elif request.algorithm == 'Bispectrum':
        # equilateral B(k, k, k) per unit-width shell via the
        # streaming Scoccimarro estimator (docs/BISPECTRUM.md): one
        # shell-filtered field resident at a time, so peak residency
        # stays under the memory_plan(workload='bispectrum') price.
        # The triangle-count normalization is seed-independent mesh
        # geometry — enumerated exactly on the host here and baked
        # into the program as constants.
        import numpy as np
        from ..algorithms.bispectrum import (_shell_edges2,
                                             shell_filtered_field)
        nbins = int(request.nbins or 4)
        nmesh = int(pm.Nmesh[0])
        edges2, kedges = _shell_edges2(nbins, pm.BoxSize)
        V = float(np.prod(pm.BoxSize))

        # ordered (q1, q2) pairs in shell b whose mod-N closure
        # q3 = -(q1 + q2) lands back in shell b — the same aliased
        # closure the mesh product sums over
        M = nbins + 1
        r = np.arange(-M, M + 1)
        g = np.stack(np.meshgrid(r, r, r, indexing='ij'),
                     axis=-1).reshape(-1, 3)
        isq = (g ** 2).sum(axis=1)
        T = np.zeros(nbins, dtype='f8')
        for b in range(nbins):
            qs = g[(isq >= edges2[b, 0]) & (isq < edges2[b, 1])]
            tot = 0
            for lo in range(0, qs.shape[0], 2048):
                q3 = (-(qs[lo:lo + 2048, None, :] + qs[None, :, :])
                      + nmesh // 2) % nmesh - nmesh // 2
                s3 = (q3 ** 2).sum(axis=-1)
                tot += int(((s3 >= edges2[b, 0])
                            & (s3 < edges2[b, 1])).sum())
            T[b] = float(tot)
        # B = V^2 * sum_x(d^3) / (Ntot * ntri); empty shells report 0
        # (finite, so shadow verification stays bit-comparable)
        norm = jnp.asarray(
            np.where(T > 0, V * V / np.where(T > 0, T, 1.0)
                     / float(pm.Ntot), 0.0), jnp.float32)
        ntri_c = jnp.asarray(T, jnp.float32)
        kmid = jnp.asarray(0.5 * (kedges[1:] + kedges[:-1]),
                           jnp.float32)
        e2 = [(int(edges2[b, 0]), int(edges2[b, 1]))
              for b in range(nbins)]

        def single(seed):
            c = _delta_c(pm, _uniform_pos(seed, npart, L), resampler,
                         npart)
            Bs = []
            for lo2, hi2 in e2:
                d = shell_filtered_field(pm, c, lo2, hi2)
                Bs.append(jnp.sum(d * d * d))
            B = jnp.stack(Bs).astype(jnp.float32) * norm
            return kmid, B, ntri_c

    else:  # FFTCorr: inverse transform of the 3-d power -> xi(r)
        def single(seed):
            import numpy as np
            c = _delta_c(pm, _uniform_pos(seed, npart, L), resampler,
                         npart)
            from ..ops.window import compensation_transfer
            w = pm.k_list(dtype=jnp.float32, circular=True)
            c = compensation_transfer(resampler, False)(w, c)
            p3c = (c * jnp.conj(c)).at[0, 0, 0].set(0.0)
            xi3 = pm.c2r(p3c.astype(c.dtype))
            # integer-lattice radial shells in real space (periodic
            # signed distance per axis)
            nmesh = int(pm.Nmesh[0])
            nbins = nmesh // 2
            ax = [jnp.asarray(np.minimum(np.arange(n),
                                         n - np.arange(n))
                              .astype('i4')).reshape(
                      [1 if i != j else -1 for j in range(3)])
                  for i, n in enumerate(int(v) for v in pm.Nmesh)]
            from ..ops.histogram import lattice_shell_index
            dsq = ax[0] ** 2 + ax[1] ** 2 + ax[2] ** 2
            shell = lattice_shell_index(dsq, nbins)
            flat = jnp.broadcast_to(shell, xi3.shape).reshape(-1)
            S = jnp.zeros(nbins, jnp.float32).at[flat].add(
                xi3.astype(jnp.float32).reshape(-1))
            Nm = jnp.zeros(nbins, jnp.float32).at[flat].add(
                jnp.ones_like(flat, jnp.float32))
            x = jnp.asarray(np.arange(nbins, dtype='f4')) \
                * jnp.float32(L / nmesh)
            return x, S / jnp.maximum(Nm, 1.0), Nm

    return single


class Program(object):
    """One warm compiled analysis program, bound to one sub-mesh.

    ``batchable`` programs (1-device sub-meshes: plain jax ops, no
    shard_map) take a ``(B,)`` seed array and vmap over realizations;
    multi-device programs take one seed per launch.
    """

    __slots__ = ('key', 'label', 'mesh', 'batchable', '_fn', '_device',
                 'data', '_pm', '_resampler')

    def __init__(self, request, mesh):
        import jax
        from ..pmesh import ParticleMesh
        self.key = request.program_key(mesh_size(mesh))
        self.label = program_label(request)
        self.mesh = mesh
        self.data = getattr(request, 'data_ref', None) is not None
        self._pm = None
        self._resampler = request.resampler
        if self.data:
            # data programs are never vmap-batched: their input is a
            # streamed catalog, not a seed array.  The pm is kept — the
            # eager ingest paints on it; only field -> spectrum is jit.
            self.batchable = False
            self._device = None
            pm = ParticleMesh(request.nmesh, BOX_SIZE, request.dtype,
                              comm=mesh)
            self._pm = pm
            # memoized-by-ProgramCache lifetime (see below)
            # nbkl: disable=NBK202
            self._fn = instrumented_jit(_build_data(request, pm),
                                        label=self.label)
            return
        self.batchable = mesh_size(mesh) == 1
        if self.batchable:
            # comm-less plain-ops form — the ONLY form vmap can batch
            # (shard_map is not vmappable); placement happens by
            # committing the seed input to the sub-mesh's one device
            self._device = mesh.devices.item() if mesh is not None \
                else None
            from ..parallel.runtime import use_mesh
            with use_mesh(None):
                pm = ParticleMesh(request.nmesh, BOX_SIZE,
                                  request.dtype)
            single = _build_single(request, pm)
            # ProgramCache memoizes Program per (program_key, worker,
            # opts) — __init__ runs once per cache entry, so this jit
            # cache is long-lived, not per-call
            # nbkl: disable=NBK202
            self._fn = instrumented_jit(jax.vmap(single),
                                        label=self.label)
        else:
            self._device = None
            pm = ParticleMesh(request.nmesh, BOX_SIZE, request.dtype,
                              comm=mesh)
            # same memoized-by-ProgramCache lifetime as above
            # nbkl: disable=NBK202
            self._fn = instrumented_jit(_build_single(request, pm),
                                        label=self.label)

    def run(self, seeds):
        """Execute for a list of seeds; returns per-seed
        (x, y, nmodes) numpy triples.  Multi-device programs run the
        seeds sequentially (their parallelism is the mesh); 1-device
        programs run them as one vmapped launch."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        if self.batchable:
            arr = jnp.asarray(list(seeds), jnp.uint32)
            if self._device is not None:
                arr = jax.device_put(arr, self._device)
            x, y, nm = self._fn(arr)
            x, y, nm = (np.asarray(v) for v in (x, y, nm))
            return [(x[i], y[i], nm[i]) for i in range(len(seeds))]
        out = []
        from ..parallel.runtime import use_mesh
        with use_mesh(self.mesh):
            for s in seeds:
                x, y, nm = self._fn(jnp.uint32(s))
                out.append(tuple(np.asarray(v) for v in (x, y, nm)))
        return out

    def run_data(self, ref, cache=None, fits=None, overlap=None):
        """Execute a ``data_ref`` program: stream (or cache-hit) the
        catalog onto this sub-mesh, then run the warm field->spectrum
        executable.  Returns ``([(x, y, nmodes)], ingest_stats)`` —
        the stats carry cache_hit / bytes / seconds so the server can
        expose ingestion throughput per request."""
        import numpy as np
        from ..ingest.stream import ingest_catalog
        from ..parallel.runtime import use_mesh
        with use_mesh(self.mesh):
            field, _, stats = ingest_catalog(
                ref, self._pm, resampler=self._resampler, cache=cache,
                fits=fits, overlap=overlap)
            x, y, nm = self._fn(field)
            out = tuple(np.asarray(v) for v in (x, y, nm))
        return [out], stats


class ProgramCache(object):
    """(program key, worker) -> warm :class:`Program`, plus the
    once-per-shape-class tuned-option resolution.  All counters are
    exported: ``serve.program.build`` / ``.reuse`` and
    ``serve.tuned.resolve`` / ``.reuse`` tell the doctor how warm the
    server is running."""

    def __init__(self):
        self._lock = threading.Lock()
        self._programs = {}
        self._tuned = {}

    def tuned_options(self, request, ndevices):
        """The TUNE_CACHE.json resolution for this shape class —
        memoized so a thousand same-class requests cost one lookup."""
        key = (request.shape_class, request.dtype, int(ndevices))
        with self._lock:
            hit = self._tuned.get(key)
        if hit is not None:
            counter('serve.tuned.reuse').add(1)
            return hit
        from ..tune.resolve import resolve_paint
        cfg = resolve_paint(nmesh=request.nmesh, npart=request.npart,
                            dtype=request.dtype, nproc=ndevices)
        cfg = {k: v for k, v in cfg.items()
               if k in ('paint_method', 'paint_chunk_size',
                        'paint_streams') and v is not None
               and v != 'auto'}
        counter('serve.tuned.resolve').add(1)
        with self._lock:
            self._tuned.setdefault(key, cfg)
        return cfg

    def get(self, request, mesh, worker, opts=None):
        """The warm program for (request shape, worker), building it
        on first use.  ``opts`` (request-scoped option overrides) are
        part of the key: jit never sees Python option globals, so a
        degraded run traced under smaller chunks must NOT share an
        executable with the clean-option trace."""
        key = (request.program_key(mesh_size(mesh)), int(worker),
               tuple(sorted((opts or {}).items())))
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                counter('serve.program.reuse').add(1)
                return prog
            # build under the lock: two threads must not race the
            # same (key, worker) into two instrumented wrappers
            prog = Program(request, mesh)
            self._programs[key] = prog
        counter('serve.program.build').add(1)
        return prog

    def __len__(self):
        with self._lock:
            return len(self._programs)


def affinity(request, ndevices, n_workers):
    """The worker whose cache this request's program warms: stable
    across the request stream (hash of the program key), so identical
    shapes land where their executable already lives.  ``data_ref``
    requests salt the hash with the catalog path: repeat requests
    against one survey land on the worker whose CatalogCache already
    holds it (the cache-hit-to-paint route), while distinct surveys of
    the same shape spread."""
    key = request.program_key(ndevices)
    if getattr(request, 'data_ref', None) is not None:
        key = key + (request.data_ref.get('path'),)
    return hash(key) % max(n_workers, 1)


def rank(ticket):
    """Sort key: higher priority first, then earliest deadline, then
    submission order."""
    return (-ticket.request.priority, ticket.deadline_at, ticket.seq)
