"""QoS fair-share: named service classes + per-tenant token buckets.

The fleet queue already ranks by priority/deadline — but priority is
*self-declared*: a bulk SBI sweep that stamps ``priority=2`` on ten
thousand requests jumps every interactive P(k) query in the queue, and
no per-request rank function can tell an urgent tenant from a lying
one.  Fair share has to key on WHO is asking, not on what they claim:
each tenant draws from a token bucket whose refill rate comes from the
tenant's *service class* (assigned by the operator, not the request),
so a flood from one tenant throttles that tenant and nobody else —
starvation becomes a provable failure mode instead of a production
surprise (``tests/test_region.py`` runs the same flood with and
without the policy; docs/SERVING.md "Region").

Buckets are reservation-style (tokens may go negative): each request
over the burst gets a monotonically growing due-time, computed purely
from arithmetic on the refill rate — deterministic, testable without
wall-clock races.  A class with ``rate=None`` is unthrottled (the
interactive default): its requests never wait and its deadline
evictions count as *starvation* in the region scorecard.

Chaos grammar: every reservation passes the ``region.qos.admit``
fault point, so an injected ``internal`` error proves the region
converts a broken QoS gate into a structured ``qos_unavailable``
rejection — never a lost request.
"""

import threading

from ...diagnostics import counter
from ...resilience.faults import fault_point


class ServiceClass(object):
    """One named QoS tier.

    ``rate`` is the sustained per-tenant admission rate in requests/s
    (None = unthrottled); ``burst`` is the bucket depth — how many
    requests a tenant may land instantly before the rate binds
    (defaults to ``rate``).
    """

    __slots__ = ('name', 'rate', 'burst')

    def __init__(self, name, rate=None, burst=None):
        self.name = str(name)
        if rate is not None:
            rate = float(rate)
            if rate <= 0:
                raise ValueError('ServiceClass rate must be positive '
                                 'or None (got %r)' % rate)
        self.rate = rate
        self.burst = float(burst) if burst is not None \
            else (rate if rate is not None else None)

    def __repr__(self):
        return 'ServiceClass(%r, rate=%r, burst=%r)' % (
            self.name, self.rate, self.burst)


#: The default tiers: interactive flows untouched, batch sustains a
#: steady clip, bulk is the firehose that must never drown the others.
DEFAULT_CLASSES = (
    ServiceClass('interactive', rate=None),
    ServiceClass('batch', rate=16.0, burst=32),
    ServiceClass('bulk', rate=4.0, burst=8),
)


class _Bucket(object):
    """Reservation token bucket: ``reserve`` returns the seconds the
    caller must wait before its slot arrives (0.0 = admit now).
    Tokens go negative past the burst, so the Nth over-burst request
    waits ``N / rate`` — the leaky-bucket due-time ladder."""

    __slots__ = ('rate', 'burst', 'tokens', 'stamp')

    def __init__(self, rate, burst):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = None

    def reserve(self, now):
        if self.stamp is None:
            self.stamp = now
        self.tokens = min(self.burst,
                          self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now
        self.tokens -= 1.0
        if self.tokens >= 0.0:
            return 0.0
        return -self.tokens / self.rate


class QoSPolicy(object):
    """Tenant -> service class mapping plus the per-tenant buckets.

    Parameters
    ----------
    classes : iterable of :class:`ServiceClass` (default
        :data:`DEFAULT_CLASSES`)
    tenants : dict tenant-name -> class-name; unmapped tenants fall to
        ``default_class``
    default_class : class name for unknown tenants ('interactive' —
        an unconfigured tenant must never be silently throttled)
    """

    def __init__(self, classes=None, tenants=None,
                 default_class='interactive'):
        self.classes = {c.name: c for c in (classes or DEFAULT_CLASSES)}
        if default_class not in self.classes:
            raise ValueError('default_class %r not among classes %s'
                             % (default_class, sorted(self.classes)))
        self.tenants = dict(tenants or {})
        for t, cname in self.tenants.items():
            if cname not in self.classes:
                raise ValueError('tenant %r maps to unknown class %r '
                                 '(valid: %s)'
                                 % (t, cname, sorted(self.classes)))
        self.default_class = default_class
        self._lock = threading.Lock()
        self._buckets = {}
        self.throttled = 0

    def service_class(self, tenant):
        """The :class:`ServiceClass` governing ``tenant``."""
        return self.classes[self.tenants.get(str(tenant),
                                             self.default_class)]

    def reserve(self, tenant, now):
        """``(class_name, delay_s)`` for one request from ``tenant``
        at monotonic time ``now``.  ``delay_s == 0`` admits
        immediately; otherwise the caller holds the request until its
        due-time (or evicts it with a structured verdict when the
        wait would blow the deadline).  Chaos point:
        ``region.qos.admit``."""
        fault_point('region.qos.admit')
        cls = self.service_class(tenant)
        if cls.rate is None:
            return cls.name, 0.0
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = _Bucket(cls.rate,
                                                         cls.burst)
            delay = bucket.reserve(now)
            if delay > 0.0:
                self.throttled += 1
        if delay > 0.0:
            counter('region.qos.throttled').add(1)
        return cls.name, delay

    def stats(self):
        with self._lock:
            return {'tenants': len(self._buckets),
                    'throttled': self.throttled,
                    'classes': sorted(self.classes)}
