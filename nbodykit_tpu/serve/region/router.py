"""The region front door: N fleets, one ``submit``.

A :class:`Region` stands in front of N independent
:class:`~nbodykit_tpu.serve.server.AnalysisServer` fleets and gives
tenants the same four-verb surface one fleet has — ``submit`` /
``wait`` / ``drain`` / ``shutdown`` — while adding what one fleet
cannot: placement *across* fleets, memoization of completed results,
fair share between tenants, and membership that grows at runtime.

The submit path, in order:

1. **Result cache** (:mod:`.result_cache`): the request's content
   address is computed on the submitting thread and looked up first —
   a hit is served immediately with zero FLOPs, zero queueing and
   zero QoS cost (a memoized answer is free; throttling it would be
   pure spite).
2. **QoS gate** (:mod:`.qos`): misses pass the tenant's fair-share
   bucket.  An over-rate tenant's request is *held* to its due-time
   by the pacer thread (or evicted with a structured
   ``qos_throttled`` verdict when the due-time would blow its own
   deadline) — it is never silently dropped and it never crowds the
   fleet queues.
3. **Router** (:class:`RegionRouter`): catalog-affine placement keyed
   on the content address — the PR-13 worker-placement idiom lifted
   to fleet granularity — spilling to the least-loaded fleet with a
   structured redirect verdict, health-checked via each fleet's
   live load/accepting surface so a dead or preempted fleet is
   routed around, not into.
4. **Harvest**: the fleet's verdict is re-wrapped with the routing
   verdict and region-level latency; a COMPLETED seeded-or-data
   result is committed to the cache (stamped ``verified`` only when
   the execution was shadow-verified).

Every region submission ends as exactly one
:class:`~nbodykit_tpu.serve.server.RequestResult`; ``summary()['lost']``
is the number the doctor FAILs on, exactly as at fleet scope.
"""

import heapq
import threading
import time

from ...diagnostics import (counter, current_tracer, gauge,
                            new_request_context, span, trace_context,
                            trace_scope)
from ...diagnostics.export import FLIGHT, ensure_exporter, \
    register_source
from ...diagnostics.slo import SLOTracker
from ...resilience.faults import corrupt_spec
from ..scheduler import affinity
from ..server import COMPLETED, EVICTED, REJECTED, RequestResult
from .result_cache import result_key


class Fleet(object):
    """One named fleet behind the region front door."""

    __slots__ = ('name', 'server')

    def __init__(self, name, server):
        self.name = str(name)
        self.server = server
        if getattr(server, 'name', None) is None \
                and hasattr(server, 'set_name'):
            # label the member for the export plane: its queue-depth
            # gauges and SLO source carry this fleet name from now on
            server.set_name(self.name)

    def load(self):
        """The router's health/load probe: the fleet's live queue
        surface (cheap — one lock, no device work)."""
        return self.server.load()

    def __repr__(self):
        return 'Fleet(%r, %d workers)' % (self.name,
                                          len(self.server.meshes))


class RegionRouter(object):
    """Catalog-affine fleet placement with structured verdicts.

    Placement is the scheduler's worker-affinity idiom lifted one
    level: ``hash(program_key [+ data_ref path]) % n_fleets``, so
    identical programs land where their executables are warm and
    repeat surveys land where their catalog is resident.  ``data_ref``
    paths get *sticky homes* — once a catalog has been ingested
    somewhere, later requests follow it there even when the hash says
    otherwise (the resident copy beats a cold re-ingest) — until a
    membership change re-homes them (:meth:`rehome_locked`).

    Verdict grammar (every route returns one structured dict):

    - ``{'code': 'affinity', 'fleet': F, 'depth': d}`` — the hash
      said F and F is healthy and shallow enough.
    - ``{'code': 'catalog_home', 'fleet': F}`` — a sticky data_ref
      home.
    - ``{'code': 'spill', 'fleet': G, 'from': F, 'from_depth': d0,
      'depth': d1}`` — F is over ``spill_depth``; G is the
      least-loaded healthy fleet.
    - ``{'code': 'rerouted_dead', 'fleet': G, 'from': F}`` — F is
      not accepting (dead, preempted, shut down).
    - ``{'code': 'no_fleet', 'fleets': n}`` — nothing in the region
      accepts; the region rejects with this reason.
    """

    def __init__(self, fleets, spill_depth=8):
        self.lock = threading.Lock()
        self._fleets = list(fleets)
        self.spill_depth = int(spill_depth)
        # path -> {'fleet': name, 'salt': int}: the sticky data_ref
        # homes; 'salt' re-derives the hash slot at rehome time
        self._homes = {}
        self.rehomed = 0

    def fleets(self):
        with self.lock:
            return list(self._fleets)

    def get(self, name):
        with self.lock:
            for f in self._fleets:
                if f.name == name:
                    return f
        raise KeyError('no fleet named %r in the region' % name)

    def add_locked(self, fleet):
        """Append a member (caller holds :attr:`lock` — the join seal
        boundary)."""
        if any(f.name == fleet.name for f in self._fleets):
            raise ValueError('fleet name %r already in the region'
                             % fleet.name)
        self._fleets.append(fleet)

    def rehome_locked(self):
        """Re-derive every sticky catalog home over the new member
        count — the live-CatalogCache analogue of
        :func:`~nbodykit_tpu.resilience.fleet.repartition`: ownership
        is reassigned deterministically from the new count at the
        seal boundary.  A moved catalog pays one cold ingest at its
        new home while the old copy ages out of that fleet's LRU (the
        device arrays cannot teleport between fleets).  Returns the
        number of homes that moved."""
        n = len(self._fleets)
        moved = 0
        for path, home in list(self._homes.items()):
            name = self._fleets[home['salt'] % n].name
            if name != home['fleet']:
                home['fleet'] = name
                moved += 1
        self.rehomed += moved
        if moved:
            counter('region.elastic.rehomed').add(moved)
        return moved

    @staticmethod
    def _accepting(fleet):
        try:
            return bool(fleet.load().get('accepting'))
        except Exception:       # pragma: no cover - dying fleet
            return False

    @staticmethod
    def _depth(fleet):
        try:
            state = fleet.load()
            depth = int(state.get('queued', 0)) \
                + int(state.get('inflight', 0))
            gauge('region.fleet.load', fleet=fleet.name).set(depth)
            return depth
        except Exception:       # pragma: no cover - dying fleet
            return 1 << 30

    def route(self, request):
        """The structured placement verdict for ``request`` (see the
        class docstring for the grammar).  Pure decision — nothing is
        submitted here.

        The router lock covers only the membership/home snapshots and
        the final home write: the ``_accepting``/``_depth`` probes go
        to each fleet's ``AnalysisServer.load()`` (which takes the
        server's own lock and, behind a dying fleet, can stall), and
        holding the router lock across them would park every
        concurrent submit — and the pacer's rehome — behind the
        slowest fleet's health probe (NBK803)."""
        with self.lock:
            fleets = list(self._fleets)
            home = None
            path = None
            if getattr(request, 'data_ref', None) is not None:
                path = request.data_ref.get('path')
                home = dict(self._homes.get(path) or ())
        n = len(fleets)
        healthy = [f for f in fleets if self._accepting(f)]
        if not healthy:
            return {'code': 'no_fleet', 'fleets': n,
                    'detail': 'no accepting fleet in the region'}
        if home:
            for f in healthy:
                if f.name == home['fleet']:
                    return {'code': 'catalog_home',
                            'fleet': f.name}
            # resident home is dead: fall through to the
            # affinity hash and re-home below
        # the PR-13 placement idiom at fleet granularity: the
        # ndevices argument is pinned to 1 so the hash keys
        # content identity, not any one fleet's sub-mesh width
        aff = fleets[affinity(request, 1, n)]
        if not self._accepting(aff):
            target = min(healthy, key=self._depth)
            verdict = {'code': 'rerouted_dead',
                       'fleet': target.name, 'from': aff.name,
                       'detail': 'affinity fleet not accepting'}
        else:
            depth = self._depth(aff)
            target = aff
            verdict = {'code': 'affinity', 'fleet': aff.name,
                       'depth': depth}
            if depth > self.spill_depth:
                spill = min(healthy, key=self._depth)
                sdepth = self._depth(spill)
                if spill is not aff and sdepth < depth:
                    target = spill
                    verdict = {'code': 'spill',
                               'fleet': spill.name,
                               'from': aff.name,
                               'from_depth': depth,
                               'depth': sdepth,
                               'detail': 'affinity fleet over '
                                         'spill depth %d'
                                         % self.spill_depth}
        if path is not None:
            with self.lock:
                self._homes[path] = {'fleet': target.name,
                                     'salt': hash((path,))}
        return verdict


class RegionTicket(object):
    """One region submission: the request, its tenant/class, the
    routing verdict, and (once dispatched) the inner fleet ticket."""

    __slots__ = ('request', 'tenant', 'class_name', 'throttleable',
                 'submitted_at', 'seq', 'verdict', 'digest',
                 'key_text', 'fleet', 'inner', 'done', 'dispatched',
                 'result', 'followers', 'ctx', 'ctx_owned')

    def __init__(self, request, tenant, submitted_at, seq):
        self.request = request
        self.tenant = str(tenant)
        self.class_name = None
        self.throttleable = False
        self.submitted_at = submitted_at
        self.seq = seq
        self.verdict = None
        self.digest = None
        self.key_text = None
        self.fleet = None
        self.inner = None
        self.done = threading.Event()
        self.dispatched = threading.Event()
        self.result = None
        # singleflight: identical concurrent requests attach here and
        # are served from this leader's committed result.  None once
        # the leader has finished (sealed — late arrivals recompute).
        self.followers = []
        # the request's trace context, carried explicitly because the
        # pacer and leader-finish threads predate every request — the
        # contextvar cannot reach them (diagnostics/trace.py)
        self.ctx = None
        self.ctx_owned = False


class Region(object):
    """The multi-fleet front door (see the module docstring).

    Parameters
    ----------
    fleets : list of :class:`Fleet`, or of ``(name, server)`` pairs
    result_cache : :class:`.result_cache.ResultCache` or None —
        content-addressed memoization of completed results
    qos : :class:`.qos.QoSPolicy` or None — per-tenant fair share
        (None admits everything immediately: the policy-free region
        is the starvation-prone one the tests prove against)
    spill_depth : queue depth at which the affinity fleet spills to
        the least-loaded one
    checkpoint : :class:`~nbodykit_tpu.resilience.fleet
        .FleetCheckpointStore` or None — when given, every
        :meth:`join` seals a membership manifest stamped
        ``reformed_from``/``reformed_to`` (docs/SERVING.md "Region")
    """

    _CKPT_KEY = 'region'

    def __init__(self, fleets, result_cache=None, qos=None,
                 spill_depth=8, checkpoint=None):
        members = [f if isinstance(f, Fleet) else Fleet(*f)
                   for f in fleets]
        if not members:
            raise ValueError('a region needs at least one fleet')
        self.router = RegionRouter(members, spill_depth=spill_depth)
        self.cache = result_cache
        self.qos = qos
        self.store = checkpoint
        # the canonical sub-mesh width result addresses use: results
        # are device-count invariant by construction (the suite
        # asserts bit-identity across widths), so one width keys them
        # all; computing it here keeps result_key on the submitting
        # thread, where the tenant's option scope lives
        self._key_ndevices = members[0].server.ndevices
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._held = []
        self._tickets = []
        self.results = {}
        self._submitted = 0
        self._seq = 0
        self._accepting = True
        self._stop = False
        self._started_at = time.monotonic()
        self._routed = {}
        self._class_lat = {}
        self._class_counts = {}
        self._starved = 0
        self._qos_evicted = 0
        self._unverified_as_verified = 0
        self._leaders = {}      # digest -> inflight leader ticket
        self._joins = []
        self.slo = SLOTracker()
        register_source('region', self.slo.snapshot)
        ensure_exporter()
        self._pacer = threading.Thread(target=self._pace,
                                       name='region-pacer',
                                       daemon=True)
        self._pacer.start()

    # -- lifecycle --------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    def drain(self, timeout=None):
        """Harvest every accepted ticket's verdict (held tickets wait
        for their due-time first).  True when fully drained."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            with self._lock:
                pending = [t for t in self._tickets
                           if not t.done.is_set()]
            if not pending:
                return True
            left = None if deadline is None \
                else deadline - time.monotonic()
            if left is not None and left <= 0:
                return False
            self.wait(pending[0], timeout=left)
            if deadline is not None \
                    and time.monotonic() >= deadline \
                    and not pending[0].done.is_set():
                return False

    def _stop_pacer(self):
        """Stop the QoS pacer thread and wait for it — idempotent by
        contract, not convention: safe from ``drain`` + ``shutdown``
        in either order, from two racing ``shutdown`` calls, and from
        a pacer that already exited.  Anything still on the hold heap
        comes back for a structured eviction (never silence)."""
        with self._cv:
            held = [t for _, _, t in self._held]
            self._held = []
            self._stop = True
            self._cv.notify_all()
        pacer = self._pacer
        if pacer is not None and pacer.is_alive() and \
                pacer is not threading.current_thread():
            pacer.join(timeout=5.0)
        return held

    def shutdown(self, drain=True, timeout=None, fleets=True):
        """Stop accepting, optionally drain, stop the pacer, and (by
        default) shut the member fleets down too.  Anything still
        held by the pacer gets a structured ``shutdown`` eviction —
        never silence.  Idempotent."""
        with self._cv:
            self._accepting = False
        if drain:
            self.drain(timeout=timeout)
        for t in self._stop_pacer():
            self._finish(t, RequestResult(
                t.request.request_id, EVICTED,
                reason={'code': 'shutdown',
                        'detail': 'region shut down while held by '
                                  'fair-share pacing'},
                algorithm=t.request.algorithm,
                shape_class=t.request.shape_class))
        if fleets:
            for f in self.router.fleets():
                f.server.shutdown(drain=drain, timeout=timeout)

    # -- submission -------------------------------------------------------

    def submit(self, request, tenant='default'):
        """Admit one request from ``tenant``.  Returns a
        :class:`RegionTicket`; rejections, throttle evictions and
        result-cache hits resolve immediately."""
        now = time.monotonic()
        counter('region.submitted').add(1)
        with self._lock:
            self._submitted += 1
            self._seq += 1
            ticket = RegionTicket(request, tenant, now, self._seq)
            self._tickets.append(ticket)
            accepting = self._accepting
        # trace identity: the region is the outermost front door, so
        # it normally mints the request's context here (adopting an
        # ambient one only when a caller nested us inside a trace)
        ctx = trace_context()
        owns_ctx = ctx is None
        if owns_ctx and current_tracer() is not None:
            ctx = new_request_context(request.request_id)
        ticket.ctx = ctx
        ticket.ctx_owned = bool(owns_ctx)
        with trace_scope(ctx if owns_ctx else None), \
                span('region.submit', request_id=request.request_id,
                     tenant=ticket.tenant,
                     algorithm=request.algorithm) as sp:
            if owns_ctx and ctx is not None and not ctx.span_id:
                # this span IS the request's root: every cross-thread
                # span re-parents to it via ctx.span_id
                ctx.span_id = sp.span_id
            return self._submit_gated(ticket, request, tenant, now,
                                      accepting)

    def _submit_gated(self, ticket, request, tenant, now, accepting):
        if not accepting:
            self._finish(ticket, RequestResult(
                request.request_id, REJECTED,
                reason={'code': 'shutting_down',
                        'detail': 'region no longer accepting '
                                  'requests'},
                algorithm=request.algorithm,
                shape_class=request.shape_class))
            return ticket
        if self.qos is not None:
            # label the class up front (no token spent) so cache hits
            # and followers land in the right by_class row
            ticket.class_name = self.qos.service_class(tenant).name
        # 1. the result cache: a memoized answer is free — served
        # before the QoS gate (throttling zero FLOPs helps nobody)
        if self.cache is not None:
            digest, text = result_key(request,
                                      ndevices=self._key_ndevices)
            ticket.digest, ticket.key_text = digest, text
            entry = self.cache.get(digest)
            if entry is not None:
                self._serve_hit(ticket, entry, now)
                return ticket
            # singleflight: an identical request already inflight is
            # the leader; attach and be served from its commit (a
            # closed-loop slam of repeats computes each answer once)
            with self._lock:
                leader = self._leaders.get(digest)
                if leader is not None and leader.followers is not None:
                    leader.followers.append(ticket)
                    self._routed['follower'] = \
                        self._routed.get('follower', 0) + 1
                    counter('region.result_cache.followers').add(1)
                    tr = current_tracer()
                    if tr is not None and ticket.ctx is not None \
                            and leader.ctx is not None:
                        # zero-duration link span: ties the follower's
                        # waterfall to the leader's trace it rides on
                        tr.emit_span(
                            'region.singleflight.follower',
                            time.time(), 0.0,
                            {'request_id': request.request_id,
                             'leader_trace': leader.ctx.trace_id,
                             'leader_request':
                                 leader.request.request_id},
                            ctx=ticket.ctx)
                    return ticket
                self._leaders[digest] = ticket
        # 2. the QoS gate
        if self.qos is not None:
            try:
                cname, delay = self.qos.reserve(tenant, now)
            except Exception as e:
                # a broken gate (chaos: region.qos.admit) rejects
                # with a structured verdict — never loses the request
                counter('region.qos.failed').add(1)
                self._finish(ticket, RequestResult(
                    request.request_id, REJECTED,
                    reason={'code': 'qos_unavailable',
                            'error': str(e)[:200],
                            'type': type(e).__name__},
                    latency_s=time.monotonic() - now,
                    algorithm=request.algorithm,
                    shape_class=request.shape_class))
                return ticket
            ticket.class_name = cname
            ticket.throttleable = \
                self.qos.service_class(tenant).rate is not None
            if delay > 0.0:
                if delay >= request.deadline_s:
                    self._qos_evict(ticket, delay, now)
                    return ticket
                with self._cv:
                    if self._stop:
                        pass        # raced shutdown; fall through
                    else:
                        heapq.heappush(self._held,
                                       (now + delay, ticket.seq,
                                        ticket))
                        gauge('region.qos.held').set(len(self._held))
                        self._cv.notify_all()
                        return ticket
        # 3. route + submit
        self._dispatch(ticket)
        return ticket

    def _qos_evict(self, ticket, delay, now):
        with self._lock:
            self._qos_evicted += 1
        self._finish(ticket, RequestResult(
            ticket.request.request_id, EVICTED,
            reason={'code': 'qos_throttled',
                    'would_wait_s': round(delay, 3),
                    'deadline_s': ticket.request.deadline_s,
                    'detail': 'fair-share due-time past the '
                              'request deadline'},
            latency_s=time.monotonic() - now,
            algorithm=ticket.request.algorithm,
            shape_class=ticket.request.shape_class))

    def _serve_hit(self, ticket, entry, now):
        """Deliver a result-cache hit: zero FLOPs, the honest
        ``verified`` stamp, and the hash-checked bytes.  The
        ``region.result.stamp`` corrupt rule flips the stamp here so
        CI proves the doctor catches an unverified hit served as
        verified."""
        verified = bool(entry['verified'])
        stamped = verified
        if corrupt_spec('region.result.stamp'):
            stamped = True
        if stamped and not verified:
            with self._lock:
                self._unverified_as_verified += 1
            counter('region.result_cache.unverified_stamp').add(1)
        ticket.verdict = {'code': 'result_cache',
                          'digest': ticket.digest,
                          'verified': stamped}
        with self._lock:
            self._routed['result_cache'] = \
                self._routed.get('result_cache', 0) + 1
        tr = current_tracer()
        if tr is not None and ticket.ctx is not None:
            tr.emit_span('region.cache.hit', time.time(), 0.0,
                         {'request_id': ticket.request.request_id,
                          'digest': ticket.digest,
                          'verified': stamped}, ctx=ticket.ctx)
        self._finish(ticket, RequestResult(
            ticket.request.request_id, COMPLETED,
            x=entry['x'], y=entry['y'], nmodes=entry['nmodes'],
            latency_s=time.monotonic() - now,
            events=[{'kind': 'result_cache',
                     'digest': ticket.digest, 'verified': stamped}],
            algorithm=ticket.request.algorithm,
            shape_class=ticket.request.shape_class))

    def _dispatch(self, ticket):
        """Route and hand ``ticket`` to its fleet (submit thread,
        pacer thread, or a leader's finishing thread).  Runs under the
        ticket's trace scope so ``region.route`` — and the fleet's
        whole ``serve.submit`` subtree — land in the request's trace
        whichever thread dispatches it."""
        with trace_scope(ticket.ctx):
            self._dispatch_traced(ticket)

    def _dispatch_traced(self, ticket):
        now = time.monotonic()
        if now >= ticket.submitted_at + ticket.request.deadline_s:
            self._finish(ticket, RequestResult(
                ticket.request.request_id, EVICTED,
                reason={'code': 'deadline',
                        'waited_s': round(now - ticket.submitted_at,
                                          3),
                        'detail': 'deadline passed while held by '
                                  'fair-share pacing'},
                latency_s=now - ticket.submitted_at,
                algorithm=ticket.request.algorithm,
                shape_class=ticket.request.shape_class))
            return
        with span('region.route',
                  request_id=ticket.request.request_id,
                  tenant=ticket.tenant):
            verdict = self.router.route(ticket.request)
        ticket.verdict = verdict
        with self._lock:
            self._routed[verdict['code']] = \
                self._routed.get(verdict['code'], 0) + 1
        counter('region.route.%s' % verdict['code']).add(1)
        if verdict['code'] == 'no_fleet':
            self._finish(ticket, RequestResult(
                ticket.request.request_id, REJECTED,
                reason=dict(verdict),
                latency_s=time.monotonic() - ticket.submitted_at,
                algorithm=ticket.request.algorithm,
                shape_class=ticket.request.shape_class))
            return
        fleet = self.router.get(verdict['fleet'])
        ticket.fleet = fleet
        ticket.inner = fleet.server.submit(ticket.request)
        ticket.dispatched.set()

    # -- the pacer --------------------------------------------------------

    def _pace(self):
        """Drain the fair-share hold queue: dispatch each held ticket
        at its due-time (deadline-checked at dispatch)."""
        while True:
            with self._cv:
                if self._stop:
                    return
                if not self._held:
                    self._cv.wait(timeout=0.2)
                    continue
                due, _, ticket = self._held[0]
                now = time.monotonic()
                if due > now:
                    self._cv.wait(timeout=min(due - now, 0.2))
                    continue
                heapq.heappop(self._held)
                gauge('region.qos.held').set(len(self._held))
            tr = current_tracer()
            if tr is not None and ticket.ctx is not None:
                # the hold is over: stamp it retroactively as one
                # out-of-band span covering submit -> due-time
                held_s = max(time.monotonic() - ticket.submitted_at,
                             0.0)
                tr.emit_span('region.qos.hold',
                             time.time() - held_s, held_s,
                             {'request_id':
                                  ticket.request.request_id,
                              'tenant': ticket.tenant,
                              'class': ticket.class_name},
                             ctx=ticket.ctx)
            self._dispatch(ticket)

    # -- harvest ----------------------------------------------------------

    def wait(self, ticket, timeout=None):
        """Block for a ticket's terminal region
        :class:`RequestResult` (harvesting — and memoizing — the
        fleet verdict when the ticket was dispatched)."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while not ticket.done.is_set():
            left = None if deadline is None \
                else deadline - time.monotonic()
            if left is not None and left <= 0:
                return ticket.result
            if ticket.inner is not None:
                res = ticket.fleet.server.wait(ticket.inner,
                                               timeout=left)
                if res is not None:
                    self._deliver(ticket, res)
                break
            ticket.dispatched.wait(timeout=left if left is not None
                                   else 0.2)
        return ticket.result

    def _deliver(self, ticket, res):
        """Re-wrap a fleet verdict as the region verdict: region
        latency (hold time included), the routing verdict as an
        event, and the memoization commit for completed results."""
        with self._lock:
            if ticket.done.is_set():
                return
        if res.status == COMPLETED and ticket.digest is not None \
                and self.cache is not None:
            # verified == this exact execution was shadow-compared
            # on a second sub-mesh and delivered (a mismatch would
            # have retried or failed before reaching here)
            with trace_scope(ticket.ctx), \
                    span('region.cache.commit',
                         request_id=res.request_id,
                         digest=ticket.digest):
                self.cache.put(ticket.digest, ticket.key_text,
                               res.x, res.y, res.nmodes,
                               verified=bool(getattr(ticket.inner,
                                                     'verify',
                                                     False)))
        events = list(res.events)
        events.append(dict(ticket.verdict or {}, kind='route'))
        self._finish(ticket, RequestResult(
            res.request_id, res.status, x=res.x, y=res.y,
            nmodes=res.nmodes, reason=res.reason,
            latency_s=time.monotonic() - ticket.submitted_at,
            events=events, options=res.options,
            admit_options=res.admit_options,
            batch_size=res.batch_size, algorithm=res.algorithm,
            shape_class=res.shape_class))

    def _finish(self, ticket, result):
        cls = ticket.class_name or 'unclassified'
        with self._lock:
            if ticket.done.is_set():    # pragma: no cover - idem
                return
            # seal the singleflight: late identical arrivals after
            # this point become their own leaders (and, when this run
            # completed, immediate cache hits)
            followers, ticket.followers = ticket.followers, None
            if ticket.digest is not None \
                    and self._leaders.get(ticket.digest) is ticket:
                del self._leaders[ticket.digest]
            self.results[result.request_id] = result
            counts = self._class_counts.setdefault(
                cls, {'completed': 0, 'rejected': 0, 'evicted': 0,
                      'failed': 0})
            counts[result.status] = counts.get(result.status, 0) + 1
            if result.status == COMPLETED \
                    and result.latency_s is not None:
                self._class_lat.setdefault(cls, []).append(
                    result.latency_s)
            if result.status == EVICTED \
                    and (result.reason or {}).get('code') \
                    == 'deadline' and not ticket.throttleable:
                # an unthrottled-class (or policy-free) request dying
                # of old age in a queue IS starvation — the failure
                # mode the QoS layer exists to prevent
                self._starved += 1
                counter('region.qos.starved').add(1)
            ticket.result = result
        counter('region.%s' % result.status).add(1)
        reason_code = (result.reason or {}).get('code')
        if result.status == COMPLETED:
            slo_status = 'completed'
        elif result.status == EVICTED:
            slo_status = ('deadline_evicted'
                          if reason_code == 'deadline'
                          else 'qos_throttled'
                          if reason_code == 'qos_throttled'
                          else 'cancelled')
        elif result.status == REJECTED:
            slo_status = ('qos_unavailable'
                          if reason_code == 'qos_unavailable'
                          else 'rejected')
        else:
            slo_status = result.status      # 'failed'
        self.slo.observe(cls, result.latency_s, slo_status)
        tr = current_tracer()
        if tr is not None and ticket.ctx is not None:
            tr.event('region.deliver',
                     {'request_id': result.request_id,
                      'status': result.status,
                      'latency_s': result.latency_s},
                     ctx=ticket.ctx)
        if ticket.ctx_owned:
            # this region owns the request's flight-recorder entry
            # (the fleet underneath sees an adopted context and
            # stays quiet)
            FLIGHT.record({
                'request_id': result.request_id,
                'trace': ticket.ctx.trace_id if ticket.ctx else None,
                'layer': 'region', 'status': result.status,
                'class': cls, 'tenant': ticket.tenant,
                'slo_status': slo_status,
                'latency_s': result.latency_s})
        ticket.done.set()
        ticket.dispatched.set()
        with self._cv:
            self._cv.notify_all()
        for f in (followers or ()):
            entry = None
            if result.status == COMPLETED and self.cache is not None \
                    and f.digest is not None:
                # a real cache read: hash-verified bytes, honest hit
                # accounting — the follower IS the repeat customer
                entry = self.cache.get(f.digest)
            if entry is not None:
                self._serve_hit(f, entry, f.submitted_at)
            else:
                # the leader did not commit a servable result (failed,
                # evicted, rejected, or the entry was torn): the
                # follower recomputes through the normal path
                self._dispatch(f)

    # -- elastic grow -----------------------------------------------------

    def join(self, server, name=None):
        """Absorb a newly arrived fleet at a seal boundary (the
        inverse of shrink-to-survive): routing pauses, the member
        list grows, sticky catalog homes repartition over the new
        count, and — when the region has a checkpoint store — the
        membership manifest is sealed stamped
        ``reformed_from``/``reformed_to``.  Returns the join info."""
        with self.router.lock:
            old = len(self.router._fleets)
            fleet = server if isinstance(server, Fleet) \
                else Fleet(name or 'fleet-%d' % old, server)
            self.router.add_locked(fleet)
            new = old + 1
            rehomed = self.router.rehome_locked()
            names = [f.name for f in self.router._fleets]
            homes = {p: h['fleet']
                     for p, h in self.router._homes.items()}
        counter('region.elastic.joins').add(1)
        from ...diagnostics import current_tracer
        tr = current_tracer()
        if tr is not None:
            tr.event('region.elastic.join',
                     {'from': old, 'to': new, 'fleet': fleet.name})
        info = {'fleet': fleet.name, 'reformed_from': old,
                'reformed_to': new, 'rehomed': rehomed}
        if self.store is not None:
            from .elastic import seal_join
            sealed = seal_join(self.store, self._CKPT_KEY,
                               {'fleets': names, 'homes': homes},
                               new_nranks=new, reformed_from=old)
            info['manifest_seq'] = sealed['seq']
        with self._lock:
            self._joins.append(info)
        return info

    # -- reporting --------------------------------------------------------

    @staticmethod
    def _pctile(values, q):
        if not values:
            return None
        vs = sorted(values)
        idx = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
        return vs[idx]

    def summary(self):
        """The region scorecard: the fleet-level ledger lifted one
        level, plus routing verdict counts, the result-cache posture
        (hit rate, corrupt entries, the ``unverified_as_verified``
        count the doctor FAILs on), the QoS fair-share ledger
        (throttled / starved / per-class latency), and the elastic
        join history."""
        with self._lock:
            results = list(self.results.values())
            submitted = self._submitted
            held = len(self._held)
            pending = sum(1 for t in self._tickets
                          if not t.done.is_set())
            routed = dict(self._routed)
            class_lat = {k: list(v)
                         for k, v in self._class_lat.items()}
            class_counts = {k: dict(v)
                            for k, v in self._class_counts.items()}
            starved = self._starved
            qos_evicted = self._qos_evicted
            unverified = self._unverified_as_verified
            joins = list(self._joins)
        by_status = {}
        for r in results:
            by_status[r.status] = by_status.get(r.status, 0) + 1
        cache = self.cache.stats() if self.cache is not None else None
        if cache is not None:
            looked = cache['hits'] + cache['misses']
            cache['hit_rate'] = round(cache['hits'] / looked, 4) \
                if looked else None
            cache['unverified_as_verified'] = unverified
        by_class = {}
        for cls in sorted(set(class_lat) | set(class_counts)):
            lat = class_lat.get(cls, [])
            by_class[cls] = dict(
                class_counts.get(cls, {}),
                n=sum(class_counts.get(cls, {}).values()),
                p50_s=self._pctile(lat, 0.50),
                p99_s=self._pctile(lat, 0.99))
        fleets = {f.name: f.server.summary()
                  for f in self.router.fleets()}
        wall = max(time.monotonic() - self._started_at, 1e-9)
        completed = by_status.get(COMPLETED, 0)
        return {
            'submitted': submitted,
            'resolved': len(results),
            'lost': submitted - len(results) - pending,
            'completed': completed,
            'rejected': by_status.get(REJECTED, 0),
            'evicted': by_status.get(EVICTED, 0),
            'failed': by_status.get('failed', 0),
            'held': held,
            'rps': completed / wall,
            'wall_s': wall,
            'fleet_count': len(fleets),
            'routed': routed,
            'result_cache': cache,
            'qos': {'enabled': self.qos is not None,
                    'throttled': self.qos.throttled
                    if self.qos is not None else 0,
                    'qos_evicted': qos_evicted,
                    'starved': starved},
            'by_class': by_class,
            'slo': self.slo.snapshot(),
            'elastic': {'joins': len(joins),
                        'rehomed': self.router.rehomed,
                        'events': joins},
            'fleets': fleets,
        }
