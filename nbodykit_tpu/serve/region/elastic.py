"""Elastic grow: absorb newly arrived hosts at a seal boundary.

PR 11 taught the fleet to *shrink* to survive — a re-formed fleet of
fewer hosts adopts the last sealed checkpoint, repartitioned.  This
module is the inverse: when new hosts arrive, the region grows a
membership at the same seal boundary with the same machinery —
:func:`~nbodykit_tpu.resilience.fleet.repartition` re-slices the
sealed shard arrays over the *larger* rank count (``np.array_split``
along axis 0 handles growth exactly as it handles shrinkage), every
new rank commits its shard, and the new seal's manifest is stamped
``reformed_from`` / ``reformed_to`` so the history records the join
the way it already records a shrink.

Two entry points:

- :func:`grow` — the generic one: take a key's latest sealed
  checkpoint at N ranks and re-seal it at M > N (or M < N — the math
  is symmetric; the *name* reflects the intended direction).
- :func:`seal_join` — the region front door's membership seal: one
  shard per member fleet, state carrying the fleet roster and sticky
  catalog homes, used by :meth:`~.router.Region.join`.

Both run on the region controller (one process writes all shards, so
``seal`` verifies against the shared filesystem alone with
``mesh=None`` — no collective, hence no NBK103 join-barrier surface).
"""

from ...diagnostics import counter, current_tracer
from ...resilience.fleet import repartition


def _load_shards(store, key, man):
    """Every rank's ``(state, arrays)`` for a sealed manifest, or None
    when any shard is torn (the seal verified them once, but disks
    rot; a grow must never replicate bytes it cannot re-verify)."""
    per_rank = []
    for r in range(int(man['nranks'])):
        got = store.store.load(store.shard_key(key, int(man['seq']),
                                               r))
        if got is None:
            return None
        per_rank.append(got)
    return per_rank


def grow(store, key, new_nranks, state=None, decomp=None):
    """Re-seal ``key``'s latest sealed checkpoint at ``new_nranks``.

    Loads the newest verifying manifest (say N ranks), repartitions
    its shard arrays to ``new_nranks`` via the same
    ``np.array_split`` re-slice ``FleetCheckpointStore.load`` uses,
    commits one shard per new rank at the next seq, and seals with
    the manifest stamped ``reformed_from=N, reformed_to=new_nranks``.

    ``state`` overrides the carried-forward rank-0 user state (None
    keeps it).  Returns the grow record ``{'seq', 'reformed_from',
    'reformed_to'}``.  Raises RuntimeError when there is no sealed
    history or a shard is torn — growing from nothing is a *first
    seal*, not a re-formation, and the caller should say so.
    """
    new_nranks = int(new_nranks)
    man = store.latest_manifest(key)
    if man is None:
        raise RuntimeError('grow(%r): no sealed checkpoint to grow '
                           'from — seal one first' % key)
    per_rank = _load_shards(store, key, man)
    if per_rank is None:
        raise RuntimeError('grow(%r): sealed seq %d has a torn '
                           'shard; cannot re-form from it'
                           % (key, int(man['seq'])))
    old = int(man['nranks'])
    if state is None:
        state = (per_rank[0][0] or {}).get('user')
    parts = repartition([arrays for _, arrays in per_rank],
                        new_nranks)
    seq = store.next_seq(key)
    for r in range(new_nranks):
        store.save_shard(key, seq, r, new_nranks, state,
                         arrays=parts[r] or None)
    store.seal(key, seq, nranks=new_nranks, rank=0, decomp=decomp,
               extra={'reformed_from': old,
                      'reformed_to': new_nranks})
    counter('region.elastic.reformed').add(1)
    tr = current_tracer()
    if tr is not None:
        tr.event('region.elastic.grow',
                 {'key': str(key), 'seq': int(seq),
                  'from': old, 'to': new_nranks})
    return {'seq': int(seq), 'reformed_from': old,
            'reformed_to': new_nranks}


def seal_join(store, key, state, new_nranks, reformed_from):
    """Seal region membership at a join boundary.

    One shard per member fleet (rank = member index), user ``state``
    carrying the roster (fleet names + sticky catalog homes), the
    manifest stamped ``reformed_from``/``reformed_to``.  Prior sealed
    membership arrays — when any exist and all verify — are
    repartitioned forward over the new count; a torn prior shard is
    simply not carried (membership state is re-derivable from the
    live region, unlike a checkpointed field)."""
    new_nranks = int(new_nranks)
    man = store.latest_manifest(key)
    parts = None
    if man is not None:
        per_rank = _load_shards(store, key, man)
        if per_rank is not None:
            arrays = [a for _, a in per_rank]
            if any(arrays):
                parts = repartition(arrays, new_nranks)
    seq = store.next_seq(key)
    for r in range(new_nranks):
        store.save_shard(key, seq, r, new_nranks, state,
                         arrays=(parts[r] or None) if parts else None)
    store.seal(key, seq, nranks=new_nranks, rank=0,
               extra={'reformed_from': int(reformed_from),
                      'reformed_to': new_nranks})
    return {'seq': int(seq), 'reformed_from': int(reformed_from),
            'reformed_to': new_nranks}
