"""Content-addressed memoization of completed ``BinnedStatistic``s.

An analysis request is a *pure function* of what it computes — the
compiled-program identity, the realization input, and the options that
reach jit.  Nothing else.  So a completed spectrum can be served again
without re-execution, to any tenant, forever — the millionth user of a
public survey pays zero FLOPs — provided the address is exactly the
purity boundary:

    (program_key, seed | catalog-digest, sorted(jit options))

Runtime-only fields — priority, deadline_s, verify, the tenant, the
request id — must NEVER key the cache: they change *how* a request is
scheduled, not *what* it computes.  :data:`JIT_OPTIONS` /
:data:`RUNTIME_OPTIONS` make the split explicit, and
``tests/test_region.py`` holds the property: every jit-reaching
option perturbs the address, every runtime field perturbs nothing.

The addressing reuses the idioms the repo already trusts:

- the **catalog digest** for ``data_ref`` requests is the same
  stat-level fingerprint discipline as the ingest plane's
  :class:`~nbodykit_tpu.ingest.cache.CatalogCache` front door
  (realpath, size, mtime_ns, column map) — O(1), and a changed file
  bumps size/mtime and misses;
- **commits** are atomic tmp+rename with a content hash over the
  canonical body (``_atomic_bytes``/``_canonical``/``_sha`` from
  :mod:`nbodykit_tpu.resilience.checkpoint`) — a torn entry fails
  hash verification and is *deleted and recomputed, never served*;
- **eviction** is LRU under a byte cap, like every cache here.

Entries carry ``verified`` — True only when the committed result came
from a shadow-verified execution (docs/INTEGRITY.md tier-1), so a hit
can honestly say "two disjoint device groups agreed on these bytes".
The stamp is part of the hash-covered body: serving an unverified
entry as verified is a doctor-FAILable offense, provable in CI via
the ``region.result.stamp`` corrupt rule.
"""

import json
import os
import threading
from collections import OrderedDict

from ...diagnostics import counter, gauge
from ...resilience.checkpoint import (_atomic_bytes, _canonical, _safe,
                                      _sha)

#: Options that reach the compiled program (or the deterministic
#: streaming/deposit order) and therefore key the result address.
#: Inclusive by policy: an over-keyed cache splits; an under-keyed one
#: serves wrong bytes.
JIT_OPTIONS = (
    'mesh_dtype', 'a2a_compress', 'resampler', 'paint_method',
    'paint_chunk_size', 'paint_bucket_slack', 'paint_streams',
    'fft_chunk_bytes', 'fft_decomp', 'fft_pencil', 'exchange_slack',
    'integrity', 'ingest_chunk_rows',
)

#: Options that only steer scheduling/telemetry — NEVER key material.
RUNTIME_OPTIONS = (
    'diagnostics', 'faults', 'tune_cache', 'io_verify_checksums',
    'ingest_overlap', 'ingest_cache_bytes', 'data_steal_grace_s',
    'telemetry_port',
)


def catalog_identity(data_ref):
    """The stat-level catalog digest for a ``data_ref`` request: the
    CatalogCache fingerprint discipline (realpath, size, mtime_ns,
    column map, reader options) folded to one sha256.  A rewritten
    file bumps size/mtime and mints a new address; the request's
    ``seed`` is ignored exactly as execution ignores it."""
    path = str(data_ref.get('path'))
    try:
        st = os.stat(path)
        stat = (os.path.realpath(path), int(st.st_size),
                int(st.st_mtime_ns))
    except OSError:
        # unreadable at addressing time: key on the path alone — the
        # fleet's admission probe owns the structured reject
        stat = (os.path.realpath(path), None, None)
    return _sha(_canonical({
        'stat': list(stat),
        'format': data_ref.get('format'),
        'columns': data_ref.get('columns'),
        'options': data_ref.get('options'),
    }))


def result_key(request, ndevices=1, options=None):
    """``(digest, canonical_text)`` — the content address of this
    request's result on an ``ndevices`` sub-mesh.

    Key material is exactly ``(program_key, seed | catalog-digest,
    sorted(jit options))``; ``options`` (request-scoped overrides,
    e.g. an admission ladder rung) are merged over the ambient
    globals, both filtered to :data:`JIT_OPTIONS`."""
    from ... import _global_options
    opts = {}
    for k in JIT_OPTIONS:
        try:
            opts[k] = _global_options[k]
        except KeyError:        # pragma: no cover - trimmed globals
            pass
    for k, v in (options or {}).items():
        if k in JIT_OPTIONS:
            opts[k] = v
    if getattr(request, 'data_ref', None) is not None:
        realization = ['data', catalog_identity(request.data_ref)]
    else:
        realization = ['seed', int(request.seed)]
    text = _canonical({
        'program': [str(p) for p in request.program_key(ndevices)],
        'input': realization,
        'options': sorted((k, str(v)) for k, v in opts.items()),
    })
    return _sha(text), text


def _encode(arr):
    import numpy as np
    a = np.asarray(arr)
    return {'dtype': str(a.dtype), 'shape': list(a.shape),
            'data': a.ravel().tolist()}


def _decode(d):
    import numpy as np
    return np.array(d['data'], dtype=d['dtype']).reshape(d['shape'])


class ResultCache(object):
    """Disk-backed LRU of completed spectra, one hash-covered
    ``<digest>.res.json`` per entry under ``root``.

    Commits are atomic (tmp+rename); reads verify the content hash
    and treat any torn/corrupt entry as a miss — counted, deleted,
    recomputed, never served.  ``budget_bytes`` bounds the summed
    entry bytes (LRU eviction; None = unbounded).  Thread-safe.
    """

    _SUFFIX = '.res.json'

    def __init__(self, root, budget_bytes=None):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.budget_bytes = None if budget_bytes is None \
            else int(budget_bytes)
        self._lock = threading.Lock()
        self._index = OrderedDict()     # digest -> file bytes
        self.hits = 0
        self.misses = 0
        self.commits = 0
        self.evictions = 0
        self.corrupt = 0
        for name in sorted(os.listdir(self.root)):
            if name.endswith(self._SUFFIX):
                path = os.path.join(self.root, name)
                try:
                    self._index[name[:-len(self._SUFFIX)]] = \
                        os.path.getsize(path)
                except OSError:     # pragma: no cover - racing rm
                    pass

    def _path(self, digest):
        return os.path.join(self.root, _safe(digest) + self._SUFFIX)

    def __len__(self):
        with self._lock:
            return len(self._index)

    def get(self, digest):
        """The committed entry for ``digest`` as ``{'x', 'y',
        'nmodes', 'verified', 'key'}`` (arrays decoded), or None.
        Hash-verifies the body; a torn or tampered file counts as
        ``region.result_cache.corrupt``, is unlinked, and misses —
        the caller recomputes."""
        path = self._path(digest)
        present = True
        try:
            with open(path) as f:
                stored = json.load(f)
        except FileNotFoundError:
            stored, present = None, False
        except (OSError, ValueError):
            # the file exists but will not parse: a torn write
            stored = None
        body = (stored or {}).get('body')
        if stored is None or not isinstance(body, dict) \
                or _sha(_canonical(body)) != stored.get('sha256'):
            with self._lock:
                self._index.pop(digest, None)
                if present:
                    # torn or hash-failing files are corruption
                    # evidence, not a cold miss
                    self.corrupt += 1
                self.misses += 1
            if present:
                counter('region.result_cache.corrupt').add(1)
                try:
                    os.unlink(path)
                except OSError:     # pragma: no cover - racing rm
                    pass
            counter('region.result_cache.misses').add(1)
            return None
        with self._lock:
            self.hits += 1
            if digest in self._index:
                self._index.move_to_end(digest)
        counter('region.result_cache.hits').add(1)
        return {'x': _decode(body['x']), 'y': _decode(body['y']),
                'nmodes': _decode(body['nmodes']),
                'verified': bool(body.get('verified')),
                'key': body.get('key')}

    def put(self, digest, key_text, x, y, nmodes, verified=False):
        """Commit one completed result under ``digest`` (atomic;
        idempotent — a concurrent twin commits identical bytes).
        Evicts LRU entries past ``budget_bytes`` first."""
        body = {'key': key_text, 'x': _encode(x), 'y': _encode(y),
                'nmodes': _encode(nmodes), 'verified': bool(verified)}
        data = json.dumps({'v': 1, 'sha256': _sha(_canonical(body)),
                           'body': body}, indent=1).encode('utf-8')
        self._ensure_room(len(data))
        _atomic_bytes(self._path(digest), data)
        with self._lock:
            self._index[digest] = len(data)
            self._index.move_to_end(digest)
            resident = sum(self._index.values())
        self.commits += 1
        counter('region.result_cache.commits').add(1)
        gauge('region.result_cache.bytes').set(resident)
        return digest

    def _ensure_room(self, incoming):
        if self.budget_bytes is None:
            return
        evicted = []
        with self._lock:
            while self._index and \
                    sum(self._index.values()) + incoming \
                    > self.budget_bytes:
                digest, _ = self._index.popitem(last=False)
                evicted.append(digest)
                self.evictions += 1
        for digest in evicted:
            try:
                os.unlink(self._path(digest))
            except OSError:         # pragma: no cover - racing rm
                pass
        if evicted:
            counter('region.result_cache.evictions').add(len(evicted))

    def stats(self):
        with self._lock:
            return {'entries': len(self._index),
                    'resident_bytes': sum(self._index.values()),
                    'hits': self.hits, 'misses': self.misses,
                    'commits': self.commits,
                    'evictions': self.evictions,
                    'corrupt': self.corrupt}
