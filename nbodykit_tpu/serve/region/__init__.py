"""The region: one front door over N analysis fleets.

One :class:`~nbodykit_tpu.serve.server.AnalysisServer` is one fleet —
one queue, one box.  A :class:`Region` is the layer above: it routes
requests to the catalog-affine fleet (spilling from hot ones with a
structured verdict), memoizes completed spectra under their content
address so repeat surveys cost zero FLOPs, grows membership when new
hosts arrive (the inverse of shrink-to-survive, sealed with
``reformed_from/to`` stamps), and holds tenants to fair-share token
buckets so a bulk sweep cannot starve interactive queries.

docs/SERVING.md "Region" is the contract; ``bench.py
--region-trace`` and the smoke region gate are the proof.
"""

from .elastic import grow, seal_join  # noqa: F401
from .qos import (DEFAULT_CLASSES, QoSPolicy,  # noqa: F401
                  ServiceClass)
from .result_cache import (JIT_OPTIONS, RUNTIME_OPTIONS,  # noqa: F401
                           ResultCache, catalog_identity, result_key)
from .router import (Fleet, Region, RegionRouter,  # noqa: F401
                     RegionTicket)

__all__ = [
    'Region', 'Fleet', 'RegionRouter', 'RegionTicket',
    'ResultCache', 'result_key', 'catalog_identity',
    'JIT_OPTIONS', 'RUNTIME_OPTIONS',
    'QoSPolicy', 'ServiceClass', 'DEFAULT_CLASSES',
    'grow', 'seal_join',
]
