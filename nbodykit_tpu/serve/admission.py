"""Admission control: price first, schedule second.

Every request is priced through :func:`nbodykit_tpu.pmesh.memory_plan`
against its target sub-mesh's HBM budget (0.85 x ``hbm_bytes`` — the
same 15% allocator margin the plan itself applies) BEFORE it can touch
the queue.  Three outcomes:

``admit``
    the plan fits as requested — no configuration changes.
``degrade``
    the plan fits only after stepping the request down the resilience
    degradation ladder (:func:`nbodykit_tpu.resilience.scoped_ladder`
    — the per-request form that writes into a private options dict,
    never the process-wide options).  The accumulated option overrides
    ride on the decision and are applied with
    :func:`nbodykit_tpu.option_scope` around just this request's
    execution.
``reject``
    no rung makes it fit (or the geometry is impossible on the
    sub-mesh).  The decision carries a STRUCTURED reason — machine
    shape, never a bare string — quoting the peak and budget it was
    judged by, so a 2048^3 request can never OOM a chip that a
    thousand small tenants are sharing, and the caller learns exactly
    why and by how much.
"""

from ..pmesh import memory_plan

# decision states
ADMIT = 'admit'
DEGRADE = 'degrade'
REJECT = 'reject'


class AdmissionDecision(object):
    """The priced verdict for one request on one sub-mesh."""

    __slots__ = ('status', 'request_id', 'plan', 'reason', 'options',
                 'rungs')

    def __init__(self, status, request_id, plan=None, reason=None,
                 options=None, rungs=None):
        self.status = status
        self.request_id = request_id
        self.plan = plan
        self.reason = reason
        self.options = dict(options or {})
        self.rungs = list(rungs or [])

    @property
    def admitted(self):
        return self.status != REJECT

    def to_dict(self):
        out = {'status': self.status, 'request_id': self.request_id,
               'options': dict(self.options),
               'rungs': [r[0] for r in self.rungs]}
        if self.reason is not None:
            out['reason'] = dict(self.reason)
        if self.plan is not None:
            out['peak_bytes'] = self.plan.get('peak_bytes')
            out['budget_bytes'] = self.plan.get('budget_bytes')
        return out

    def __repr__(self):
        return 'AdmissionDecision(%s %s%s)' % (
            self.status, self.request_id,
            ' %s' % self.reason.get('code') if self.reason else '')


def _plan(request, ndevices, hbm_bytes, paint_chunk=None,
          catalog_bytes=None):
    method = request.paint_method
    if method in (None, 'auto'):
        # price what would actually run: the tune-cache resolution for
        # this platform/shape (scheduler resolves the same way)
        from ..tune.resolve import resolve_paint
        method = resolve_paint(
            nmesh=request.nmesh, npart=request.npart,
            dtype=request.dtype, nproc=ndevices,
            # Forward runs the grad-safe resolution (a cached winner
            # with no adjoint story demotes) — price what executes
            differentiable=request.algorithm == 'Forward',
        ).get('paint_method', 'scatter')
        if method == 'auto':
            method = 'scatter'
    chunk_rows = None
    if getattr(request, 'data_ref', None) is not None:
        # a data_ref request streams+paints+transforms jointly: price
        # the resident catalog and the double-buffered staging chunks
        # alongside the mesh pipeline
        from ..ingest.stream import resolve_chunk_rows
        chunk_rows = resolve_chunk_rows(npart=request.npart,
                                        nproc=ndevices)
    # a Forward request is a forward+BACKWARD pipeline: price it with
    # the reverse-mode branch (per-step residuals held live) instead
    # of the one-shot fftpower peak; a Bispectrum request is priced by
    # its streaming 3-field shell peak (the serve path always runs the
    # FFT estimator — the direct path is a library/tuner concern)
    workload = {'Forward': 'forward',
                'Bispectrum': 'bispectrum'}.get(request.algorithm,
                                                'fftpower')
    return memory_plan(request.nmesh, request.npart,
                       ndevices=ndevices, dtype=request.dtype,
                       resampler=request.resampler,
                       paint_method=method, paint_chunk=paint_chunk,
                       hbm_bytes=hbm_bytes,
                       ingest_chunk_rows=chunk_rows,
                       catalog_bytes=catalog_bytes,
                       workload=workload,
                       pm_steps=getattr(request, 'pm_steps', None),
                       nbins=getattr(request, 'nbins', None))


def catalog_fits_fn(request, ndevices=1, hbm_bytes=16e9):
    """The catalog-cache eviction predicate for one admitted data_ref
    request: ``fits(total_resident_bytes)`` is this request's
    admission plan re-priced at a candidate cache residency — the
    scheduler hands it to :meth:`CatalogCache.ensure_room` so LRU
    entries fall out exactly when memory_plan says the joint
    ingestion+paint+FFT peak would not fit beside them."""
    def fits(resident_bytes):
        return bool(_plan(request, ndevices, hbm_bytes,
                          catalog_bytes=resident_bytes)['fits'])
    return fits


def admit(request, ndevices=1, hbm_bytes=16e9):
    """Price ``request`` for an ``ndevices`` sub-mesh and decide.

    Geometry that cannot run at all (Nmesh not divisible by the
    sub-mesh, resampler support wider than a slab) rejects with
    ``code='indivisible'``; an over-budget plan walks the scoped
    degradation ladder and either admits degraded or rejects with
    ``code='over_budget'`` quoting every rung it tried.
    """
    ndevices = max(int(ndevices), 1)
    if getattr(request, 'data_ref', None) is not None:
        # open the ref NOW: an unreadable path must reject with a
        # structured verdict at admission, never fail a worker later —
        # and the file's row count becomes the npart everything else
        # (pricing, shape class, program key) is judged by
        from ..ingest.stream import IngestError, probe_ref
        try:
            info = probe_ref(request.data_ref)
        except IngestError as e:
            return AdmissionDecision(REJECT, request.request_id,
                                     reason=e.to_reason())
        if info['nrows'] < 1:
            return AdmissionDecision(REJECT, request.request_id,
                                     reason={
                'code': 'unreadable_data_ref',
                'path': request.data_ref.get('path'),
                'detail': 'catalog has zero rows'})
        request.npart = int(info['nrows'])
    if request.nmesh % ndevices:
        return AdmissionDecision(REJECT, request.request_id, reason={
            'code': 'indivisible', 'nmesh': request.nmesh,
            'ndevices': ndevices,
            'detail': 'Nmesh must be divisible by the sub-mesh size'})
    from ..ops.window import window_support
    if window_support(request.resampler) > request.nmesh // ndevices:
        return AdmissionDecision(REJECT, request.request_id, reason={
            'code': 'indivisible', 'nmesh': request.nmesh,
            'ndevices': ndevices, 'resampler': request.resampler,
            'detail': 'resampler support exceeds the per-device slab'})
    if request.algorithm == 'Forward':
        # the particle lattice is a second mesh (ng^3 = npart) and
        # must shard over the same sub-mesh
        ng = int(round(float(request.npart) ** (1.0 / 3.0)))
        if ng % ndevices:
            return AdmissionDecision(REJECT, request.request_id,
                                     reason={
                'code': 'indivisible', 'npart': request.npart,
                'ndevices': ndevices,
                'detail': 'Forward particle lattice ng=%d must be '
                          'divisible by the sub-mesh size' % ng})

    plan = _plan(request, ndevices, hbm_bytes)
    if plan['fits']:
        return AdmissionDecision(ADMIT, request.request_id, plan=plan)

    # over budget as requested: step the request-scoped ladder until
    # the re-priced plan fits or the rungs run out
    from ..resilience import scoped_ladder
    opts = {}
    ladder = scoped_ladder(opts)
    rungs = []
    while True:
        rung = ladder.step()
        if rung is None:
            break
        rungs.append(rung)
        plan2 = _plan(request, ndevices, hbm_bytes,
                      paint_chunk=opts.get('paint_chunk_size'))
        if plan2['fits']:
            return AdmissionDecision(DEGRADE, request.request_id,
                                     plan=plan2, options=opts,
                                     rungs=rungs)
    return AdmissionDecision(REJECT, request.request_id, plan=plan,
                             reason={
        'code': 'over_budget',
        'peak_bytes': int(plan['peak_bytes']),
        'budget_bytes': int(plan['budget_bytes']),
        'hbm_bytes': int(hbm_bytes),
        'nmesh': request.nmesh, 'npart': request.npart,
        'ndevices': ndevices,
        'rungs_tried': [r[0] for r in rungs],
        'detail': 'peak exceeds 0.85*HBM on every degradation rung'})
