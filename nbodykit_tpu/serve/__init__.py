"""nbodykit_tpu.serve — FFTPower-as-a-service.

The batch pipeline answers "run my analysis"; this package answers
"run EVERYONE'S analyses, continuously, on one shared fleet" — the
operating regime of a survey-scale TPU pod: a persistent,
admission-controlled, multi-tenant analysis server.

- :mod:`.request` — the declarative :class:`AnalysisRequest` (what to
  compute + deadline + priority; a few hundred bytes, no arrays —
  real-survey requests point at their catalog with ``data_ref``
  instead of ``seed`` and the ingestion plane
  (:mod:`nbodykit_tpu.ingest`) streams it onto the sub-mesh).
- :mod:`.admission` — every request priced through
  :func:`~nbodykit_tpu.pmesh.memory_plan` against the sub-mesh HBM
  budget BEFORE scheduling: admit, degrade down the request-scoped
  resilience ladder, or reject with a structured reason.
- :mod:`.scheduler` — cache-affine placement onto
  :meth:`~nbodykit_tpu.batch.TaskManager.sub_meshes` workers and the
  warm :class:`ProgramCache` (TUNE_CACHE winners resolved once per
  shape class; ``compile.serve.*`` counters prove the second
  identical-shape request compiles nothing).
- :mod:`.batching` — compatible FFTPower requests vmap-coalesced into
  one device launch, the window bounded so no deadline is blown.
- :mod:`.server` — the :class:`AnalysisServer` loop: bounded queue,
  deadline eviction with structured verdicts, per-request
  Supervisor + option scope (one tenant's fault never touches the
  fleet), graceful drain/shutdown.
- :mod:`.synth` — deterministic Zipf-popularity request traces for
  the bench/regress pipeline (``bench.py --serve-trace``,
  ``--region-trace``).
- :mod:`.region` — the layer ABOVE the fleet: a :class:`Region`
  fronts N independent servers with catalog-affine routing +
  least-loaded spill, content-addressed result memoization
  (:class:`ResultCache`), per-tenant QoS fair share
  (:class:`QoSPolicy`), and elastic membership grow sealed with
  ``reformed_from/to`` stamps (docs/SERVING.md "Region").

Quick start::

    from nbodykit_tpu.serve import AnalysisServer, AnalysisRequest
    with AnalysisServer(per_task=1) as srv:
        t = srv.submit(AnalysisRequest(nmesh=64, npart=100000))
        result = srv.wait(t)       # RequestResult: k, P(k), nmodes

CLI: ``nbodykit-tpu-serve --trace 100`` (or
``python -m nbodykit_tpu.serve``).  Guide: docs/SERVING.md.
"""

from .request import ALGORITHMS, AnalysisRequest  # noqa: F401
from .admission import (ADMIT, DEGRADE, REJECT,  # noqa: F401
                        AdmissionDecision, admit)
from .scheduler import ProgramCache, program_label  # noqa: F401
from .batching import BatchPolicy  # noqa: F401
from .server import (COMPLETED, EVICTED, FAILED,  # noqa: F401
                     REJECTED, AnalysisServer, RequestResult)
from .synth import (generate_region_trace, generate_trace,  # noqa: F401
                    replay, replay_region)
from .region import (DEFAULT_CLASSES, Fleet, QoSPolicy,  # noqa: F401
                     Region, RegionRouter, ResultCache,
                     ServiceClass, result_key)
