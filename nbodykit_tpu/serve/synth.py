"""Synthetic multi-tenant request traces.

A serving benchmark is only as honest as its load: this generator
produces the 1k-request trace the bench/regress pipeline replays —
deterministic from a seed, with the shape statistics of a real
multi-tenant analysis service:

- a small catalog of (algorithm, nmesh, npart) shapes with Zipf-ish
  popularity (probability ~ 1/(rank+1)): a few hot shapes dominate —
  the regime where the warm program cache and vmap batching pay —
  with a long tail of cold shapes that each eat one compile;
- mostly ``FFTPower`` (the batchable algorithm), a minority of
  ``ConvolvedFFTPower`` / ``FFTCorr``;
- mixed priorities and deadlines, plus a slice of deliberately
  hopeless requests (huge mesh, or sub-millisecond deadline) so the
  admission controller and the deadline evictor have real work.

Everything derives from ``random.Random(seed)`` — the same seed is
the same trace on every platform, which is what lets BENCH_r*.json
rounds compare against each other.
"""

import random

from .request import AnalysisRequest

# the shape catalog, hot-first (Zipf rank order).  Small meshes: the
# serving benchmark measures scheduling/caching/batching overheads on
# an 8-device CPU mesh, not FFT throughput.
_CATALOG = (
    ('FFTPower', 32, 20000),
    ('FFTPower', 64, 50000),
    ('FFTPower', 32, 50000),
    ('FFTCorr', 32, 20000),
    ('FFTPower', 48, 30000),
    ('ConvolvedFFTPower', 32, 20000),
    ('FFTPower', 64, 100000),
    ('FFTCorr', 64, 50000),
)


def generate_trace(n, seed=0, deadline_s=120.0, reject_fraction=0.02,
                   evict_fraction=0.0):
    """``n`` deterministic :class:`AnalysisRequest`\\ s.

    ``reject_fraction`` of them ask for an absurd mesh (2048³ on one
    device) to exercise structured rejection; ``evict_fraction`` carry
    a deadline already impossible at submission to exercise eviction.
    IDs are ``trace-NNNNN`` in submission order.
    """
    rng = random.Random(seed)
    weights = [1.0 / (rank + 1) for rank in range(len(_CATALOG))]
    out = []
    for i in range(int(n)):
        rid = 'trace-%05d' % i
        u = rng.random()
        if u < reject_fraction:
            out.append(AnalysisRequest(
                algorithm='FFTPower', nmesh=2048, npart=10 ** 9,
                dtype='f4', seed=rng.randrange(2 ** 20),
                deadline_s=deadline_s, priority=0, request_id=rid))
            continue
        algo, nmesh, npart = rng.choices(_CATALOG,
                                         weights=weights)[0]
        dl = deadline_s
        if evict_fraction and u < reject_fraction + evict_fraction:
            dl = 1e-3
        out.append(AnalysisRequest(
            algorithm=algo, nmesh=nmesh, npart=npart, dtype='f4',
            seed=rng.randrange(2 ** 20), deadline_s=dl,
            priority=rng.choice((0, 0, 0, 1, 1, 2)),
            request_id=rid))
    return out


def replay(server, trace, interarrival_s=0.0, seed=0):
    """Submit a trace to ``server`` and wait for every verdict.

    ``interarrival_s > 0`` adds exponential(ish) spacing from the same
    deterministic RNG — 0 is closed-loop slam.  Returns the ticket
    list (order matches the trace)."""
    import time
    rng = random.Random(seed)
    tickets = []
    for req in trace:
        tickets.append(server.submit(req))
        if interarrival_s > 0:
            time.sleep(rng.expovariate(1.0 / interarrival_s))
    for t in tickets:
        t.done.wait()
    return tickets
