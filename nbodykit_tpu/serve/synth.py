"""Synthetic multi-tenant request traces.

A serving benchmark is only as honest as its load: this generator
produces the 1k-request trace the bench/regress pipeline replays —
deterministic from a seed, with the shape statistics of a real
multi-tenant analysis service:

- a small catalog of (algorithm, nmesh, npart) shapes with Zipf-ish
  popularity (probability ~ 1/(rank+1)): a few hot shapes dominate —
  the regime where the warm program cache and vmap batching pay —
  with a long tail of cold shapes that each eat one compile;
- mostly ``FFTPower`` (the batchable algorithm), a minority of
  ``ConvolvedFFTPower`` / ``FFTCorr``;
- mixed priorities and deadlines, plus a slice of deliberately
  hopeless requests (huge mesh, or sub-millisecond deadline) so the
  admission controller and the deadline evictor have real work.

Everything derives from ``random.Random(seed)`` — the same seed is
the same trace on every platform, which is what lets BENCH_r*.json
rounds compare against each other.
"""

import random

from .request import AnalysisRequest

# the shape catalog, hot-first (Zipf rank order).  Small meshes: the
# serving benchmark measures scheduling/caching/batching overheads on
# an 8-device CPU mesh, not FFT throughput.
_CATALOG = (
    ('FFTPower', 32, 20000),
    ('FFTPower', 64, 50000),
    ('FFTPower', 32, 50000),
    ('FFTCorr', 32, 20000),
    ('FFTPower', 48, 30000),
    ('ConvolvedFFTPower', 32, 20000),
    ('FFTPower', 64, 100000),
    ('FFTCorr', 64, 50000),
)


def generate_trace(n, seed=0, deadline_s=120.0, reject_fraction=0.02,
                   evict_fraction=0.0):
    """``n`` deterministic :class:`AnalysisRequest`\\ s.

    ``reject_fraction`` of them ask for an absurd mesh (2048³ on one
    device) to exercise structured rejection; ``evict_fraction`` carry
    a deadline already impossible at submission to exercise eviction.
    IDs are ``trace-NNNNN`` in submission order.
    """
    rng = random.Random(seed)
    weights = [1.0 / (rank + 1) for rank in range(len(_CATALOG))]
    out = []
    for i in range(int(n)):
        rid = 'trace-%05d' % i
        u = rng.random()
        if u < reject_fraction:
            out.append(AnalysisRequest(
                algorithm='FFTPower', nmesh=2048, npart=10 ** 9,
                dtype='f4', seed=rng.randrange(2 ** 20),
                deadline_s=deadline_s, priority=0, request_id=rid))
            continue
        algo, nmesh, npart = rng.choices(_CATALOG,
                                         weights=weights)[0]
        dl = deadline_s
        if evict_fraction and u < reject_fraction + evict_fraction:
            dl = 1e-3
        out.append(AnalysisRequest(
            algorithm=algo, nmesh=nmesh, npart=npart, dtype='f4',
            seed=rng.randrange(2 ** 20), deadline_s=dl,
            priority=rng.choice((0, 0, 0, 1, 1, 2)),
            request_id=rid))
    return out


def replay(server, trace, interarrival_s=0.0, seed=0):
    """Submit a trace to ``server`` and wait for every verdict.

    ``interarrival_s > 0`` adds exponential(ish) spacing from the same
    deterministic RNG — 0 is closed-loop slam.  Returns the ticket
    list (order matches the trace)."""
    import time
    rng = random.Random(seed)
    tickets = []
    for req in trace:
        tickets.append(server.submit(req))
        if interarrival_s > 0:
            time.sleep(rng.expovariate(1.0 / interarrival_s))
    for t in tickets:
        t.done.wait()
    return tickets


#: Default region tenants: (name, weight, declared_priority).  The
#: bulk tenant self-declares priority 2 on every request — the lie the
#: QoS fair-share layer exists to defeat (priority is what a request
#: CLAIMS; the service class is what the operator ASSIGNED).
_TENANTS = (
    ('interactive-a', 0.35, None),
    ('interactive-b', 0.25, None),
    ('bulk-sweep', 0.40, 2),
)


def generate_region_trace(n, seed=0, deadline_s=120.0, tenants=None,
                          repeat_fraction=0.25, join_at=None):
    """A deterministic multi-fleet trace: ``n`` items, each either
    ``{'tenant', 'request'}`` or the scripted host-arrival event
    ``{'event': 'join'}``.

    Per-tenant Zipf popularity: each tenant draws from the shape
    catalog *rotated by its index*, so tenants have different hot
    shapes — the regime where catalog-affine fleet routing pays.
    ``repeat_fraction`` of a tenant's requests re-issue an exact
    earlier (algorithm, nmesh, npart, seed) from that tenant's own
    history — the repeat-survey slice that exercises result-cache
    hits.  ``join_at`` (a 0..1 fraction) inserts the join event at
    that point in the trace for the elastic-grow path.

    ``tenants`` is an iterable of ``(name, weight,
    declared_priority)`` (default :data:`_TENANTS`, whose bulk tenant
    stamps ``priority=2`` on everything — deliberately abusive).
    """
    rng = random.Random(seed)
    tenants = list(tenants) if tenants is not None else list(_TENANTS)
    names = [t[0] for t in tenants]
    weights = [float(t[1]) for t in tenants]
    declared = {t[0]: t[2] for t in tenants}
    zipf = [1.0 / (rank + 1) for rank in range(len(_CATALOG))]
    history = {name: [] for name in names}
    out = []
    join_idx = None if join_at is None \
        else max(0, min(int(n), int(float(join_at) * int(n))))
    for i in range(int(n)):
        if i == join_idx:
            out.append({'event': 'join'})
        tenant = rng.choices(names, weights=weights)[0]
        past = history[tenant]
        if past and rng.random() < repeat_fraction:
            algo, nmesh, npart, rseed = rng.choice(past)
        else:
            ti = names.index(tenant)
            rotated = _CATALOG[ti % len(_CATALOG):] \
                + _CATALOG[:ti % len(_CATALOG)]
            algo, nmesh, npart = rng.choices(rotated,
                                             weights=zipf)[0]
            rseed = rng.randrange(2 ** 20)
            past.append((algo, nmesh, npart, rseed))
        prio = declared[tenant]
        if prio is None:
            prio = rng.choice((0, 0, 1, 1, 2))
        out.append({'tenant': tenant, 'request': AnalysisRequest(
            algorithm=algo, nmesh=nmesh, npart=npart, dtype='f4',
            seed=rseed, deadline_s=deadline_s, priority=prio,
            request_id='region-%05d' % i)})
    if join_idx is not None and join_idx >= int(n):
        out.append({'event': 'join'})
    return out


def replay_region(region, items, interarrival_s=0.0, seed=0,
                  on_join=None):
    """Replay a region trace: submit each ``{'tenant', 'request'}``
    item under its tenant; at a ``{'event': 'join'}`` item call
    ``on_join(region)`` (the caller supplies the arriving fleet —
    ignored when None).  Waits for every verdict; returns the ticket
    list in submission order.

    Region delivery is harvest-on-wait, so a concurrent harvester
    thread waits each ticket as soon as it exists — a verdict is
    harvested (and its latency clocked) when the fleet finishes, not
    when the submission loop gets around to it.  With paced arrivals
    (``interarrival_s > 0``) a tail-end wait loop would otherwise
    charge the whole remaining replay wall to every early request."""
    import threading
    import time
    rng = random.Random(seed)
    tickets = []
    done_submitting = threading.Event()
    stop = threading.Event()

    def _harvest():
        # bounded waits so a stop request is always honored within
        # one poll interval, even mid-wait on a wedged ticket
        i = 0
        while not stop.is_set():
            if i < len(tickets):
                region.wait(tickets[i], timeout=0.25)
                if tickets[i].done.is_set():
                    i += 1
            elif done_submitting.is_set():
                return
            else:
                time.sleep(0.005)

    def _stop_harvester(drain):
        # idempotent by contract: safe to call twice, safe after the
        # harvester already exited, and the exception path (drain=
        # False) never hangs the caller behind an undelivered verdict
        done_submitting.set()
        if not drain:
            stop.set()
        if harvester.is_alive() and \
                harvester is not threading.current_thread():
            harvester.join(None if drain else 2.0)

    harvester = threading.Thread(target=_harvest, daemon=True,
                                 name='region-replay-harvest')
    harvester.start()
    try:
        for item in items:
            if 'event' in item:
                if item['event'] == 'join' and on_join is not None:
                    on_join(region)
                continue
            tickets.append(region.submit(item['request'],
                                         tenant=item['tenant']))
            if interarrival_s > 0:
                time.sleep(rng.expovariate(1.0 / interarrival_s))
    except BaseException:
        _stop_harvester(drain=False)
        raise
    _stop_harvester(drain=True)
    return tickets
