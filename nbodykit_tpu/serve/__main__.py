"""Serve CLI: run the analysis server against a synthetic trace.

    nbodykit-tpu-serve --trace 100      (== python -m nbodykit_tpu.serve)
        Generate a deterministic 100-request trace, replay it through
        an :class:`~nbodykit_tpu.serve.AnalysisServer` on the local
        devices, print the serving scorecard (and exit 1 if any
        request was lost without a structured verdict).

    Options: --trace N · --seed S · --per-task K (devices per worker
    sub-mesh) · --max-batch B · --max-delay-ms MS (batch window) ·
    --max-queue Q · --hbm-gb G (admission budget is 0.85x this) ·
    --deadline-s D · --devices N (CPU: force N virtual devices) ·
    --json PATH (write the full summary + per-request verdicts).

Fault injection rides the usual channel: ``NBKIT_FAULTS`` (e.g.
``serve.request.attempt@3:unavailable``) — survived faults show in
the scorecard's retried/degraded/resumed columns.  The 1k-request
benchmark round lives in ``bench.py --serve-trace`` (same machinery,
BENCH-stamped).
"""

import argparse
import json
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='nbodykit-tpu-serve',
        description='replay a synthetic multi-tenant trace through '
                    'the analysis server')
    ap.add_argument('--trace', type=int, default=100,
                    help='number of requests to generate (default 100)')
    ap.add_argument('--seed', type=int, default=0)
    ap.add_argument('--per-task', type=int, default=1)
    ap.add_argument('--max-batch', type=int, default=8)
    ap.add_argument('--max-delay-ms', type=float, default=20.0)
    ap.add_argument('--max-queue', type=int, default=1024)
    ap.add_argument('--hbm-gb', type=float, default=16.0)
    ap.add_argument('--deadline-s', type=float, default=300.0)
    ap.add_argument('--devices', type=int, default=None)
    ap.add_argument('--json', default=None,
                    help='write summary + per-request verdicts here')
    args = ap.parse_args(argv)

    if args.devices:
        from .._jax_compat import set_cpu_devices
        set_cpu_devices(args.devices)

    import nbodykit_tpu  # noqa: F401  (option/env wiring)
    from . import AnalysisServer, BatchPolicy, generate_trace, replay

    trace = generate_trace(args.trace, seed=args.seed,
                           deadline_s=args.deadline_s)
    server = AnalysisServer(
        per_task=args.per_task, max_queue=args.max_queue,
        hbm_bytes=args.hbm_gb * 1e9,
        batch=BatchPolicy(max_batch=args.max_batch,
                          max_delay_s=args.max_delay_ms / 1e3))
    with server:
        replay(server, trace, seed=args.seed)
        summary = server.summary()

    if args.json:
        from ..diagnostics import atomic_write
        payload = dict(summary, verdicts=[
            r.to_dict() for _, r in sorted(server.results.items())])
        atomic_write(args.json,
                     json.dumps(payload, indent=1, sort_keys=True))

    for key in ('submitted', 'completed', 'rejected', 'evicted',
                'failed', 'lost', 'retried', 'fault_degraded',
                'resumed', 'admit_degraded', 'programs'):
        print('%-16s %s' % (key, summary[key]))
    for key in ('p50_s', 'p99_s', 'rps'):
        v = summary[key]
        print('%-16s %s' % (key, '%.4f' % v if v is not None else '-'))
    return 1 if summary['lost'] else 0


if __name__ == '__main__':
    sys.exit(main())
