"""Batching compatible small-mesh requests into one device program.

A 1-device program is plain jax ops under ``vmap``, so N FFTPower
requests with the SAME program key become one launch with a leading
batch dimension over realization seeds.  The rules that keep this
honest:

- only ``batchable`` programs batch (multi-device programs are
  shard_map, which vmap cannot wrap);
- only requests admitted CLEAN batch — a degraded admission carries
  per-request option overrides that would have to apply to the whole
  launch, so degraded requests always run solo;
- the collection window never blows a deadline: a batch closes as
  soon as waiting any longer would make the tightest deadline in it
  (or in the candidate) unservable, bounded by ``max_delay_s``;
- seed counts are padded up to the next power of two (repeating the
  last seed) so the compiled-shape catalog stays logarithmic in batch
  size — pad results are discarded after the launch.
"""

from ..diagnostics import counter

_PAD_LIMIT = 1 << 10


class BatchPolicy(object):
    """Knobs for the batching window.

    ``max_batch`` — most requests per launch; ``max_delay_s`` — the
    longest a ready request may wait for company.  ``max_delay_s=0``
    disables coalescing entirely (every request runs solo).
    """

    __slots__ = ('max_batch', 'max_delay_s')

    def __init__(self, max_batch=8, max_delay_s=0.05):
        self.max_batch = max(int(max_batch), 1)
        self.max_delay_s = max(float(max_delay_s), 0.0)


def compatible(ticket, other, ndevices):
    """True when ``other`` may join ``ticket``'s launch: identical
    program key, both clean admissions (no per-request overrides)."""
    if ticket.decision.options or other.decision.options:
        return False
    return ticket.request.program_key(ndevices) \
        == other.request.program_key(ndevices)


def pad_seeds(seeds):
    """Pad the seed list up to the next power of two by repeating the
    last seed; returns (padded, real_count).  Callers slice results to
    ``real_count`` — the pads are pure compile-shape insulation."""
    n = len(seeds)
    cap = 1
    while cap < n and cap < _PAD_LIMIT:
        cap <<= 1
    padded = list(seeds) + [seeds[-1]] * (cap - n)
    if cap > n:
        counter('serve.batch.padded').add(cap - n)
    return padded, n


def close_window(now, tickets, policy, opened_at):
    """Should a batch opened at ``opened_at`` stop waiting for company?

    True when the batch is full, coalescing is off, the window has
    been open ``max_delay_s`` already, or waiting any longer would
    push the tightest member deadline past its limit — the window
    NEVER blows a deadline that admission accepted."""
    if len(tickets) >= policy.max_batch:
        return True
    if policy.max_delay_s <= 0:
        return True
    if now - opened_at >= policy.max_delay_s:
        return True
    tightest = min(t.deadline_at for t in tickets)
    return now + policy.max_delay_s >= tightest
