"""The analysis server: a persistent, admission-controlled loop.

One :class:`AnalysisServer` owns the device fleet for its lifetime.
Devices are partitioned into fixed sub-meshes exactly the way
:meth:`nbodykit_tpu.batch.TaskManager.map` partitions them
(:meth:`~nbodykit_tpu.batch.TaskManager.sub_meshes`), one long-lived
worker thread pinned per sub-mesh.  A request's life:

1. **submit** — priced by :func:`.admission.admit` against the
   sub-mesh HBM budget; a rejection (or a full queue) returns a
   structured :class:`RequestResult` immediately, never an exception.
2. **queue** — a single bounded priority view shared by the workers;
   ranking is priority desc, deadline asc, submission order.  Expired
   tickets are evicted WITH a structured verdict at every pop — a
   deadline miss is an answer, not a disappearance.
3. **place** — cache-affine: the worker at
   ``hash(program_key) % n_workers`` owns the warm executable; an
   idle worker steals the best-ranked foreign ticket rather than
   idle through a backlog.
4. **batch** — compatible clean-admission FFTPower tickets on a
   1-device sub-mesh coalesce into one vmap launch
   (:mod:`.batching`), the collection window capped so no member's
   deadline is blown.
5. **run** — under a per-request :class:`~nbodykit_tpu.resilience.Supervisor`
   (fault point ``serve.request.attempt``) with a request-scoped
   degradation ladder writing into THAT request's option overrides,
   applied via :func:`nbodykit_tpu.option_scope` — an injected tunnel
   death retries/degrades one request; the other tenants never see it.
   With a checkpoint store, finished work is saved before the
   post-work fault point ``serve.request.work`` so a kill after
   compute resumes instead of recomputing.
6. **deliver** — every submitted request ends as exactly one
   :class:`RequestResult`; ``lost`` (submitted minus resolved) is the
   number the doctor FAILs on.

Observability: ``serve.request`` spans, ``serve.*`` counters, a
``serve.queue_depth`` gauge and a ``serve.latency_s`` histogram; the
server additionally keeps the raw per-request latency list so
:meth:`AnalysisServer.summary` can report real p50/p99 (the streaming
histogram keeps only moments).
"""

import threading
import time

from ..diagnostics import (counter, current_tracer, gauge, histogram,
                           new_request_context, span, trace_context,
                           trace_scope)
from ..diagnostics.export import FLIGHT, ensure_exporter, \
    register_source
from ..diagnostics.slo import SLOTracker
from ..parallel.runtime import mesh_size
from .admission import REJECT, admit
from .batching import BatchPolicy, close_window, compatible, pad_seeds
from .scheduler import ProgramCache, affinity, rank

# terminal request states
COMPLETED = 'completed'
REJECTED = 'rejected'
EVICTED = 'evicted'
FAILED = 'failed'


def _resolve_data_steal_grace(value):
    """The effective data-steal grace window in seconds: the
    ``data_steal_grace_s`` option when set, else
    ``$NBKIT_DATA_STEAL_GRACE_S``, else the class default (1.0).
    Must parse as a non-negative finite float (0 = steal freely)."""
    import math
    import os
    source = 'set_options(data_steal_grace_s=...)'
    if value in (None, 'auto'):
        value = os.environ.get('NBKIT_DATA_STEAL_GRACE_S')
        source = '$NBKIT_DATA_STEAL_GRACE_S'
        if value is None:
            return AnalysisServer.DATA_STEAL_GRACE_S
    try:
        grace = float(value)
    except (TypeError, ValueError):
        grace = -1.0
    if not math.isfinite(grace) or grace < 0:
        raise ValueError(
            'data_steal_grace_s must be a non-negative finite '
            'number of seconds, got %r (via %s)' % (value, source))
    return grace


class RequestResult(object):
    """The one terminal verdict every submitted request gets."""

    __slots__ = ('request_id', 'status', 'x', 'y', 'nmodes', 'reason',
                 'latency_s', 'events', 'options', 'admit_options',
                 'batch_size', 'algorithm', 'shape_class',
                 'queue_wait_s', 'service_s')

    def __init__(self, request_id, status, x=None, y=None, nmodes=None,
                 reason=None, latency_s=None, events=None, options=None,
                 admit_options=None, batch_size=0, algorithm=None,
                 shape_class=None, queue_wait_s=None, service_s=None):
        self.request_id = request_id
        self.status = status
        self.x, self.y, self.nmodes = x, y, nmodes
        self.reason = reason
        self.latency_s = latency_s
        # the latency split: time queued before a worker picked the
        # ticket vs time actually executing; latency_s remains the
        # combined end-to-end number for record compatibility
        self.queue_wait_s = queue_wait_s
        self.service_s = service_s
        self.events = list(events or [])
        # options: everything applied around the run (tuned winners +
        # overrides); admit_options: ONLY what admission stepped down
        self.options = dict(options or {})
        self.admit_options = dict(admit_options or {})
        self.batch_size = int(batch_size)
        self.algorithm = algorithm
        self.shape_class = shape_class

    @property
    def ok(self):
        return self.status == COMPLETED

    def event_count(self, kind):
        return sum(1 for e in self.events if e.get('kind') == kind)

    def to_dict(self):
        out = {'request_id': self.request_id, 'status': self.status,
               'latency_s': self.latency_s,
               'queue_wait_s': self.queue_wait_s,
               'service_s': self.service_s,
               'batch_size': self.batch_size,
               'algorithm': self.algorithm,
               'shape_class': self.shape_class,
               'options': dict(self.options),
               'admit_options': dict(self.admit_options),
               'events': list(self.events)}
        if self.reason is not None:
            out['reason'] = dict(self.reason)
        return out

    def __repr__(self):
        return 'RequestResult(%s %s%s)' % (
            self.request_id, self.status,
            ' %.3fs' % self.latency_s if self.latency_s else '')


class _Ticket(object):
    __slots__ = ('request', 'decision', 'submitted_at', 'deadline_at',
                 'seq', 'affinity', 'done', 'result', 'verify', 'ctx',
                 'ctx_owned')

    def __init__(self, request, decision, submitted_at, seq, aff,
                 verify=False, ctx=None, ctx_owned=False):
        self.request = request
        self.decision = decision
        self.submitted_at = submitted_at
        self.deadline_at = submitted_at + request.deadline_s
        self.seq = seq
        self.affinity = aff
        self.done = threading.Event()
        self.result = None
        self.verify = bool(verify)
        # the request's trace context, carried explicitly because
        # worker threads outlive (and predate) every request — the
        # contextvar cannot reach them (trace.py)
        self.ctx = ctx
        self.ctx_owned = bool(ctx_owned)


class AnalysisServer(object):
    """Multi-tenant FFTPower-as-a-service over the local device fleet.

    Parameters
    ----------
    per_task : devices per sub-mesh (1 → every worker is a 1-device
        batchable lane; the fleet is ``n_devices // per_task`` lanes)
    max_queue : bound on waiting tickets; beyond it submissions get a
        structured ``queue_full`` rejection
    hbm_bytes : per-device HBM the admission controller prices against
        (0.85x of this is the budget)
    batch : :class:`.batching.BatchPolicy`
    checkpoint : :class:`~nbodykit_tpu.resilience.CheckpointStore`
        or None — per-request resume across mid-run faults
    retry : :class:`~nbodykit_tpu.resilience.RetryPolicy` override
    verify_fraction : float in [0, 1] — deterministically sample this
        fraction of admitted seeded requests for tier-1 shadow
        verification (docs/INTEGRITY.md), on top of any request that
        sets ``verify=True`` itself.  A shadowed request re-executes
        on a different sub-mesh worker after completion and the
        results are compared — bit-identical when no lossy
        compression is in play, within :func:`~nbodykit_tpu.resilience
        .integrity.shadow_margin` otherwise.  A mismatch raises a
        classified IntegrityError, so the per-request Supervisor
        retries it once and the strike lands in the SuspectTracker.
        The shadow run needs no extra admission headroom: it executes
        the SAME priced program on the shadow worker's identical
        sub-mesh, so the request's memory_plan verdict bounds both
        executions.
    """

    def __init__(self, per_task=1, max_queue=256, hbm_bytes=16e9,
                 batch=None, checkpoint=None, retry=None,
                 verify_fraction=0.0, name=None):
        from ..batch import TaskManager
        from ..parallel.runtime import (CurrentMesh, cpu_mesh,
                                        tpu_mesh, use_mesh)
        from ..utils import is_mxu_backend
        if CurrentMesh.get() is None:
            # no ambient fleet mesh: serve the whole local device set
            fleet = tpu_mesh() if is_mxu_backend() else cpu_mesh()
            with use_mesh(fleet):
                tm = TaskManager(per_task)
                self.meshes = tm.sub_meshes()
        else:
            tm = TaskManager(per_task)
            self.meshes = tm.sub_meshes()
        if not self.meshes:
            raise RuntimeError('no device sub-meshes to serve on')
        self.ndevices = mesh_size(self.meshes[0])
        self.max_queue = int(max_queue)
        self.hbm_bytes = float(hbm_bytes)
        self.batch = batch if batch is not None else BatchPolicy()
        self.checkpoint = checkpoint
        self.retry = retry
        self.verify_fraction = min(max(float(verify_fraction), 0.0),
                                   1.0)
        self._shadow = {'verified': 0, 'mismatch': 0}
        self.programs = ProgramCache()
        # one content-addressed catalog cache per sub-mesh worker:
        # repeat data_ref requests against a survey route (via the
        # path-salted affinity) to the worker already holding it.
        # 'ingest_cache_bytes' is an optional hard cap; the per-request
        # memory_plan predicate (admission.catalog_fits_fn) prices
        # eviction either way.
        from .. import _global_options
        from ..ingest.cache import CatalogCache
        _cb = _global_options['ingest_cache_bytes']
        _cb = int(_cb) if isinstance(_cb, (int, float)) \
            and not isinstance(_cb, bool) else None
        self.catalogs = [CatalogCache(_cb) for _ in self.meshes]
        self.data_steal_grace_s = _resolve_data_steal_grace(
            _global_options['data_steal_grace_s'])

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending = []
        self._inflight = 0
        self._seq = 0
        self._stop = False
        self._accepting = True
        self._started_at = time.monotonic()

        self.results = {}
        self._latencies = []
        self._queue_waits = []
        self._service_times = []
        self._submitted = 0
        # the fleet label for the export plane's per-fleet gauges
        # (serve.queue_depth{fleet=...}); a Region names its fleets,
        # a standalone server may pass name= itself
        self.name = str(name) if name else None
        # per-shape-class SLO burn tracking; a Region layers its own
        # per-tenant-class tracker above this one
        self.slo = SLOTracker()
        register_source('serve%s' % ('.' + self.name if self.name
                                     else ''), self.slo.snapshot)
        ensure_exporter()

        self._threads = [
            threading.Thread(target=self._worker, args=(i,),
                             name='serve-worker-%d' % i, daemon=True)
            for i in range(len(self.meshes))]
        for t in self._threads:
            t.start()

    # -- lifecycle --------------------------------------------------------

    def set_name(self, name):
        """Label this fleet for the export plane (a Region names its
        member fleets at wrap time); re-registers the SLO source under
        the labelled name."""
        self.name = str(name)
        register_source('serve.' + self.name, self.slo.snapshot)
        return self

    def _depth_gauge(self, depth, inflight=None):
        """The queue-depth (and optionally inflight) gauges, both the
        process-global compatibility name and the per-fleet labelled
        series the router's spill decisions are audited against."""
        gauge('serve.queue_depth').set(depth)
        if self.name:
            gauge('serve.queue_depth', fleet=self.name).set(depth)
        if inflight is not None and self.name:
            gauge('serve.inflight', fleet=self.name).set(inflight)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    def drain(self, timeout=None):
        """Block until every accepted ticket has a result (the queue is
        empty and no worker is mid-request).  Returns True when fully
        drained."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._cv:
            while self._pending or self._inflight:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cv.wait(timeout=left if left is not None
                              else 0.5)
        return True

    def shutdown(self, drain=True, timeout=None):
        """Stop accepting, optionally drain what was accepted, stop
        the workers.  Idempotent — a second call is a no-op."""
        with self._cv:
            self._accepting = False
            already = self._stop
        if not already and drain:
            self.drain(timeout=timeout)
        with self._cv:
            # anything still queued (drain=False or timed out) gets a
            # structured eviction, never silence
            for t in self._pending:
                self._finish(t, RequestResult(
                    t.request.request_id, EVICTED,
                    reason={'code': 'shutdown',
                            'detail': 'server shut down before run'},
                    algorithm=t.request.algorithm,
                    shape_class=t.request.shape_class))
            self._pending = []
            self._stop = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)

    def preempt(self, grace_s=5.0):
        """Preemption drain: the SIGTERM response for a serving
        process (docs/RESILIENCE.md).  Stops accepting, EVICTS every
        queued ticket immediately with a structured ``preempted``
        verdict (inflight work is worth the grace budget; queued work
        is not — the client retries elsewhere), drains inflight
        requests for up to ``grace_s``, then stops the workers.  Every
        submitted request still ends with a verdict — zero lost.
        Returns ``{'evicted': n, 'drained': bool}``."""
        counter('serve.preempted').add(1)
        from ..diagnostics import current_tracer
        tr = current_tracer()
        if tr is not None:
            tr.event('resilience.preempted', {'where': 'serve'})
        with self._cv:
            self._accepting = False
            evicted = list(self._pending)
            self._pending = []
            self._depth_gauge(0)
            for t in evicted:
                self._finish(t, RequestResult(
                    t.request.request_id, EVICTED,
                    reason={'code': 'preempted',
                            'detail': 'server preempted before run'},
                    algorithm=t.request.algorithm,
                    shape_class=t.request.shape_class))
            self._cv.notify_all()
        drained = self.drain(timeout=grace_s)
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        # seal the flight recorder: the last N request waterfalls +
        # metric snapshot land next to the trace for the post-mortem
        FLIGHT.dump('serve.preempt%s' % ('.' + self.name
                                         if self.name else ''))
        return {'evicted': len(evicted), 'drained': drained}

    # -- submission -------------------------------------------------------

    def submit(self, request):
        """Admit (or reject) ``request`` and queue it.  Returns a
        ticket whose ``.done`` event / ``.result`` carry the verdict;
        rejections resolve immediately."""
        now = time.monotonic()
        counter('serve.submitted').add(1)
        # trace identity: adopt the caller's ambient context (a Region
        # dispatching) or mint a fresh one — either way the ticket
        # carries it across the queue to the worker thread
        ctx = trace_context()
        owns_ctx = ctx is None
        if owns_ctx and current_tracer() is not None:
            ctx = new_request_context(request.request_id)
        with trace_scope(ctx if owns_ctx else None), \
                span('serve.submit', request_id=request.request_id,
                     algorithm=request.algorithm,
                     shape_class=request.shape_class) as sp:
            if owns_ctx and ctx is not None and not ctx.span_id:
                # this span IS the request's root: every cross-thread
                # span re-parents to it via ctx.span_id
                ctx.span_id = sp.span_id
            return self._submit_traced(request, now, ctx, owns_ctx)

    def _submit_traced(self, request, now, ctx, owns_ctx):
        with self._lock:
            self._submitted += 1
            accepting = self._accepting
            depth = len(self._pending)
        aff = affinity(request, self.ndevices, len(self.meshes))
        if not accepting:
            from ..resilience.fleet import preemption_requested
            if preemption_requested():
                return self._reject_now(request, now, {
                    'code': 'preempted',
                    'detail': 'server preempted; retry elsewhere'},
                    ctx=ctx, ctx_owned=owns_ctx)
            return self._reject_now(request, now, {
                'code': 'shutting_down',
                'detail': 'server no longer accepting requests'},
                ctx=ctx, ctx_owned=owns_ctx)
        if depth >= self.max_queue:
            return self._reject_now(request, now, {
                'code': 'queue_full', 'depth': depth,
                'max_queue': self.max_queue,
                'detail': 'bounded queue at capacity'},
                ctx=ctx, ctx_owned=owns_ctx)
        decision = admit(request, ndevices=self.ndevices,
                         hbm_bytes=self.hbm_bytes)
        if decision.status == REJECT:
            return self._reject_now(request, now, decision.reason,
                                    decision=decision, ctx=ctx,
                                    ctx_owned=owns_ctx)
        if decision.options:
            counter('serve.admit_degraded').add(1)
        ticket = None
        with self._cv:
            self._seq += 1
            ticket = _Ticket(request, decision, now, self._seq, aff,
                             verify=self._should_verify(request),
                             ctx=ctx, ctx_owned=owns_ctx)
            self._pending.append(ticket)
            self._depth_gauge(len(self._pending))
            self._cv.notify_all()
        return ticket

    def _should_verify(self, request):
        """Whether this request gets a tier-1 shadow run: opted in via
        ``request.verify``, or deterministically sampled (a stable
        hash of the request id, not a PRNG — the same request stream
        shadows the same requests on every replay, so admission-level
        A/B comparisons stay reproducible).  data_ref requests never
        shadow (re-ingestion is not a cheap re-execution)."""
        if getattr(request, 'data_ref', None) is not None:
            return False
        if getattr(request, 'verify', False):
            return True
        if self.verify_fraction <= 0.0:
            return False
        import zlib
        h = zlib.crc32(request.request_id.encode('utf-8')) % 10000
        return h < self.verify_fraction * 10000.0

    def _reject_now(self, request, now, reason, decision=None,
                    ctx=None, ctx_owned=False):
        counter('serve.rejected').add(1)
        t = _Ticket(request, decision, now, -1, -1, ctx=ctx,
                    ctx_owned=ctx_owned)
        self._finish(t, RequestResult(
            request.request_id, REJECTED, reason=reason,
            latency_s=time.monotonic() - now,
            algorithm=request.algorithm,
            shape_class=request.shape_class))
        return t

    def wait(self, ticket, timeout=None):
        """Block for a ticket's terminal :class:`RequestResult`."""
        ticket.done.wait(timeout=timeout)
        return ticket.result

    # -- the worker loop --------------------------------------------------

    def _finish(self, ticket, result):
        ticket.result = result
        self.results[result.request_id] = result
        if result.status == COMPLETED:
            counter('serve.completed').add(1)
            if result.latency_s is not None:
                histogram('serve.latency_s').observe(result.latency_s)
                self._latencies.append(result.latency_s)
            if result.queue_wait_s is not None:
                self._queue_waits.append(result.queue_wait_s)
            if result.service_s is not None:
                self._service_times.append(result.service_s)
        elif result.status == FAILED:
            counter('serve.failed').add(1)
        elif result.status == EVICTED:
            counter('serve.evicted').add(1)
        # the SLO stream: deadline evictions burn budget, shutdown /
        # preemption / admission shedding does not (slo.py)
        if result.status == EVICTED:
            code = (result.reason or {}).get('code')
            slo_status = 'deadline_evicted' if code == 'deadline' \
                else 'cancelled'
        else:
            slo_status = result.status
        self.slo.observe(result.shape_class or 'default',
                         result.latency_s, slo_status)
        # terminal trace mark, stamped into the request's own trace
        # regardless of which thread finishes it
        tr = current_tracer()
        if tr is not None and ticket.ctx is not None:
            tr.event('serve.deliver',
                     {'request_id': result.request_id,
                      'status': result.status,
                      'latency_s': result.latency_s},
                     ctx=ticket.ctx)
        if ticket.ctx_owned:
            # front-door-less serving: this server owns the request's
            # flight-recorder entry (a Region records its own)
            FLIGHT.record({
                'request_id': result.request_id,
                'trace': ticket.ctx.trace_id if ticket.ctx else None,
                'status': result.status,
                'latency_s': result.latency_s,
                'queue_wait_s': result.queue_wait_s,
                'service_s': result.service_s,
                'shape_class': result.shape_class})
        ticket.done.set()

    def _evict_expired_locked(self, now):
        live = []
        for t in self._pending:
            if now >= t.deadline_at:
                self._finish(t, RequestResult(
                    t.request.request_id, EVICTED,
                    reason={'code': 'deadline',
                            'deadline_s': t.request.deadline_s,
                            'waited_s': round(now - t.submitted_at, 3),
                            'detail': 'deadline passed while queued'},
                    latency_s=now - t.submitted_at,
                    algorithm=t.request.algorithm,
                    shape_class=t.request.shape_class))
            else:
                live.append(t)
        self._pending = live

    # How long a data_ref ticket is reserved for its affinity worker
    # before any idle worker may steal it.  A steal pays a full
    # re-ingest onto a cold CatalogCache, so locality is worth a short
    # wait — but only a short one: a wedged affinity worker must not
    # strand the request (deadline eviction is not a placement policy).
    # The instance value resolves set_options(data_steal_grace_s=...)
    # / $NBKIT_DATA_STEAL_GRACE_S at construction; this class attr is
    # the documented default.
    DATA_STEAL_GRACE_S = 1.0

    def _pick_locked(self, wi, now):
        """Best ticket for worker ``wi``: its own affinity first, else
        steal the globally best-ranked one.  data_ref tickets resist
        stealing for ``data_steal_grace_s`` — their catalog may be
        resident in the affinity worker's cache."""
        mine = [t for t in self._pending if t.affinity == wi]
        pool = mine or [t for t in self._pending
                        if t.request.data_ref is None
                        or now - t.submitted_at
                        >= self.data_steal_grace_s]
        if not pool:
            return None
        best = min(pool, key=rank)
        self._pending.remove(best)
        return best

    def _batchable(self, ticket):
        # data_ref requests never batch: their input is a streamed
        # catalog, not a seed vmap can widen over.  Shadow-verified
        # tickets never batch either: the shadow re-run and compare
        # are per-request, and one suspect member must not force a
        # whole batch through a second execution.
        return (self.ndevices == 1
                and ticket.request.algorithm in ('FFTPower',
                                                 'Bispectrum')
                and ticket.request.data_ref is None
                and not ticket.verify
                and not ticket.decision.options)

    def _collect_locked(self, leader, opened_at):
        """Grow the leader's batch from compatible pending tickets,
        holding the coalescing window open at most ``max_delay_s`` and
        never past any member's deadline."""
        group = [leader]
        if not self._batchable(leader) \
                or self.batch.max_batch <= 1 \
                or self.batch.max_delay_s <= 0:
            return group
        while True:
            for t in list(self._pending):
                if len(group) >= self.batch.max_batch:
                    break
                if self._batchable(t) and compatible(leader, t,
                                                     self.ndevices):
                    self._pending.remove(t)
                    group.append(t)
            now = time.monotonic()
            if self._stop or close_window(now, group, self.batch,
                                          opened_at):
                return group
            self._cv.wait(timeout=self.batch.max_delay_s / 4 or 0.01)

    def _worker(self, wi):
        mesh = self.meshes[wi]
        while True:
            with self._cv:
                while True:
                    if self._stop:
                        return
                    now = time.monotonic()
                    self._evict_expired_locked(now)
                    ticket = self._pick_locked(wi, now)
                    if ticket is not None:
                        break
                    self._cv.wait(timeout=0.25)
                group = self._collect_locked(ticket, time.monotonic())
                self._inflight += 1
                self._depth_gauge(len(self._pending),
                                  inflight=self._inflight)
            try:
                self._run_group(group, mesh, wi)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    # -- execution --------------------------------------------------------

    def _run_group(self, group, mesh, wi):
        import nbodykit_tpu
        from ..resilience import Supervisor
        from ..resilience.faults import fault_point
        from ..resilience.supervise import scoped_ladder

        leader = group[0]
        req = leader.request
        if len(group) > 1:
            counter('serve.batched').add(len(group))
        # one mutable option dict per run: admission's rungs seed it,
        # the supervisor's runtime ladder steps it further on OOM —
        # both scoped to this run, applied only inside option_scope
        opts = dict(self.programs.tuned_options(req, self.ndevices))
        opts.update(leader.decision.options or {})
        sup = Supervisor('serve.request', policy=self.retry,
                         ladder=scoped_ladder(opts),
                         checkpoint=self.checkpoint)
        seeds = [t.request.seed for t in group]
        rid = req.request_id
        ingest_stats = {}

        def work():
            if req.data_ref is not None:
                return work_data()
            got = sup.resume(rid, validate=lambda s:
                             s.get('seeds') == list(seeds))
            if got is not None:
                state, arrays = got
                n = len(seeds)
                return [(arrays['x'][i], arrays['y'][i],
                         arrays['nm'][i]) for i in range(n)]
            with nbodykit_tpu.option_scope(**opts):
                prog = self.programs.get(req, mesh, wi, opts=opts)
                if prog.batchable:
                    padded, n = pad_seeds(seeds)
                    out = prog.run(padded)[:n]
                else:
                    out = prog.run(seeds)
                # the serve.result data-injection point sits HERE —
                # after compute, before verification and checkpoint —
                # so only the tier-1 shadow compare can catch it
                out = self._result_corrupt_point(out)
                if leader.verify:
                    # verify BEFORE sup.save: a corrupted result must
                    # never be checkpointed, or the retry would resume
                    # it instead of recomputing clean
                    self._shadow_verify(req, out, seeds, opts, wi)
            import numpy as np
            sup.save(rid, {'seeds': list(seeds)},
                     arrays={'x': np.array([o[0] for o in out]),
                             'y': np.array([o[1] for o in out]),
                             'nm': np.array([o[2] for o in out])})
            # the post-work fault point: a kill injected here lands
            # AFTER the checkpoint, so the retry resumes, not recomputes
            fault_point('serve.request.work')
            return out

        def work_data():
            # the streamed-catalog path: never batched (group is just
            # the leader), cache-hit routed straight to paint, evicting
            # under this request's own memory_plan predicate
            got = sup.resume(rid, validate=lambda s:
                             s.get('data_path')
                             == req.data_ref.get('path'))
            if got is not None:
                state, arrays = got
                ingest_stats.update(state.get('ingest') or {})
                return [(arrays['x'][0], arrays['y'][0],
                         arrays['nm'][0])]
            from .admission import catalog_fits_fn
            fits = catalog_fits_fn(req, ndevices=self.ndevices,
                                   hbm_bytes=self.hbm_bytes)
            counter('serve.data_requests').add(1)
            with nbodykit_tpu.option_scope(**opts):
                prog = self.programs.get(req, mesh, wi, opts=opts)
                out, stats = prog.run_data(req.data_ref,
                                           cache=self.catalogs[wi],
                                           fits=fits)
            ingest_stats.update(stats)
            import numpy as np
            sup.save(rid, {'data_path': req.data_ref.get('path'),
                           'ingest': {k: v for k, v in stats.items()
                                      if not isinstance(v, bytes)}},
                     arrays={'x': np.array([o[0] for o in out]),
                             'y': np.array([o[1] for o in out]),
                             'nm': np.array([o[2] for o in out])})
            fault_point('serve.request.work')
            return out

        now = time.monotonic()
        # the queue -> worker thread hop: re-activate the leader's
        # context (contextvars never reach this long-lived thread) and
        # retro-emit each member's queue wait into ITS OWN trace, plus
        # a zero-duration link span tying member traces to the
        # leader's (the batch runs once, under the leader's identity)
        tr = current_tracer()
        if tr is not None:
            wall = time.time()
            for t in group:
                qw = max(now - t.submitted_at, 0.0)
                if t.ctx is not None:
                    tr.emit_span('serve.queue.wait', wall - qw, qw,
                                 {'request_id': t.request.request_id,
                                  'worker': wi}, ctx=t.ctx)
            if leader.ctx is not None:
                for t in group[1:]:
                    if t.ctx is not None:
                        tr.emit_span(
                            'serve.batch.member', wall, 0.0,
                            {'request_id': t.request.request_id,
                             'leader_trace': leader.ctx.trace_id,
                             'leader_request': rid}, ctx=t.ctx)
        with trace_scope(leader.ctx), \
                span('serve.request', request_id=rid,
                     algorithm=req.algorithm,
                     shape_class=req.shape_class,
                     batch=len(group), worker=wi):
            try:
                out = sup.run(work)
            except Exception as e:
                done_at = time.monotonic()
                for t in group:
                    self._finish(t, RequestResult(
                        t.request.request_id, FAILED,
                        reason={'code': 'execution',
                                'error': str(e)[:500],
                                'type': type(e).__name__},
                        latency_s=done_at - t.submitted_at,
                        events=sup.events, options=opts,
                        admit_options=t.decision.options,
                        batch_size=len(group),
                        algorithm=t.request.algorithm,
                        shape_class=t.request.shape_class,
                        queue_wait_s=now - t.submitted_at,
                        service_s=done_at - now))
                return
        sup.done(rid)
        if sup.events:
            counter('serve.fault_degraded').add(1)
        done_at = time.monotonic()
        events = list(sup.events)
        if ingest_stats:
            # the per-request ingestion record (cache_hit, bytes,
            # seconds, chunk_rows, host peak) rides on the result as
            # an event — bench --ingest and the doctor read it there
            events.append(dict(ingest_stats, kind='ingest'))
        for t, (x, y, nm) in zip(group, out):
            self._finish(t, RequestResult(
                t.request.request_id, COMPLETED, x=x, y=y, nmodes=nm,
                latency_s=done_at - t.submitted_at, events=events,
                options=opts, admit_options=t.decision.options,
                batch_size=len(group),
                algorithm=t.request.algorithm,
                shape_class=t.request.shape_class,
                queue_wait_s=now - t.submitted_at,
                service_s=done_at - now))

    # -- tier-1 shadow verification ---------------------------------------

    def _result_corrupt_point(self, out):
        """The ``serve.result`` data-injection point: flip bits in the
        delivered spectrum of the first result when a ``corrupt`` rule
        fires (chaos grammar, docs/INTEGRITY.md).  The corruption is
        applied to the REAL result the shadow compare judges — the
        detector is what gets tested, not the injector."""
        from ..resilience.faults import corrupt_spec
        bits = corrupt_spec('serve.result')
        if not bits:
            return out
        from ..resilience.integrity import corrupt_host
        x, y, nm = out[0]
        return [(x, corrupt_host(y, bits), nm)] + list(out[1:])

    def _shadow_verify(self, req, out, seeds, opts, wi):
        """Re-execute ``req`` on the next sub-mesh worker's devices
        and compare against ``out``.  Uncompressed postures must match
        bit-for-bit (same XLA program, same backend — any divergence
        is hardware or wire corruption); compressed postures are
        judged against :func:`~nbodykit_tpu.resilience.integrity
        .shadow_margin`.  A mismatch raises a recorded
        IntegrityError(``serve.shadow``), which the per-request
        Supervisor classifies, strikes, and retries exactly once."""
        import numpy as np
        from ..resilience.integrity import shadow_margin, violation
        swi = (wi + 1) % len(self.meshes)
        with span('serve.shadow_verify', request_id=req.request_id,
                  worker=wi, shadow_worker=swi):
            sprog = self.programs.get(req, self.meshes[swi], swi,
                                      opts=opts)
            if sprog.batchable:
                padded, n = pad_seeds(seeds)
                ref = sprog.run(padded)[:n]
            else:
                ref = sprog.run(seeds)
        margin = shadow_margin(opts)
        counter('serve.shadow.verified').add(1)
        with self._lock:
            self._shadow['verified'] += 1
        for (x1, y1, n1), (x2, y2, n2) in zip(out, ref):
            delta, bad = None, None
            if not np.array_equal(np.asarray(x1), np.asarray(x2)) \
                    or not np.array_equal(np.asarray(n1),
                                          np.asarray(n2)):
                bad = 'bin geometry diverged'
            else:
                a = np.asarray(y1, np.float64)
                b = np.asarray(y2, np.float64)
                if margin <= 0.0:
                    if not np.array_equal(a, b):
                        delta = float(np.max(np.abs(a - b)))
                        bad = 'bit-identical required'
                else:
                    scale = max(float(np.max(np.abs(b))), 1e-30)
                    delta = float(np.max(np.abs(a - b))) / scale
                    if delta > margin:
                        bad = 'relative margin %.3g exceeded' % margin
                    else:
                        delta, bad = None, None
            if bad is not None:
                counter('serve.shadow.mismatch').add(1)
                with self._lock:
                    self._shadow['mismatch'] += 1
                raise violation(
                    'serve.shadow', delta=delta,
                    detail='%s (request %s, worker %d vs shadow %d)'
                           % (bad, req.request_id, wi, swi))

    # -- reporting --------------------------------------------------------

    @staticmethod
    def _pctile(values, q):
        if not values:
            return None
        vs = sorted(values)
        idx = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
        return vs[idx]

    def load(self):
        """The live load/health surface a region router probes before
        every placement: queue depth, inflight work, and whether this
        fleet still accepts — one lock, no device work, cheap enough
        to call per-route (``summary()`` is the full scorecard; this
        is the heartbeat)."""
        with self._lock:
            return {'queued': len(self._pending),
                    'inflight': self._inflight,
                    'accepting': self._accepting and not self._stop,
                    'workers': len(self.meshes)}

    def summary(self):
        """The serving scorecard: totals by terminal status, real
        p50/p99 latency, throughput, degradation provenance
        (``admit_degraded`` = stepped down at pricing;
        ``fault_degraded`` = supervisor events at runtime), and
        ``lost`` — submitted requests with NO structured verdict,
        the number that must be zero."""
        with self._lock:
            results = list(self.results.values())
            lat = list(self._latencies)
            qwaits = list(self._queue_waits)
            stimes = list(self._service_times)
            submitted = self._submitted
            queued = len(self._pending)
            inflight = self._inflight
            shadow = dict(self._shadow)
        by_status = {}
        for r in results:
            by_status[r.status] = by_status.get(r.status, 0) + 1
        by_class = {}
        for r in results:
            if r.status == COMPLETED and r.latency_s is not None:
                by_class.setdefault(r.shape_class, []).append(
                    r.latency_s)
        completed = by_status.get(COMPLETED, 0)
        wall = max(time.monotonic() - self._started_at, 1e-9)
        retried = sum(1 for r in results
                      if r.event_count('retries'))
        degraded = sum(1 for r in results
                       if r.event_count('degradations'))
        resumed = sum(1 for r in results if r.event_count('resumes'))
        admit_deg = sum(1 for r in results if r.admit_options)
        preempted = sum(
            1 for r in results
            if (r.reason or {}).get('code') == 'preempted')
        ingest_events = [e for r in results for e in r.events
                         if e.get('kind') == 'ingest']
        cat = {'entries': 0, 'resident_bytes': 0, 'hits': 0,
               'misses': 0, 'evictions': 0}
        for c in self.catalogs:
            for k, v in c.stats().items():
                cat[k] += v
        return {
            'submitted': submitted,
            'resolved': len(results),
            'lost': submitted - len(results) - queued - inflight,
            'completed': completed,
            'rejected': by_status.get(REJECTED, 0),
            'evicted': by_status.get(EVICTED, 0),
            'failed': by_status.get(FAILED, 0),
            'retried': retried,
            'fault_degraded': degraded,
            'resumed': resumed,
            'admit_degraded': admit_deg,
            'preempted': preempted,
            'p50_s': self._pctile(lat, 0.50),
            'p99_s': self._pctile(lat, 0.99),
            'mean_s': sum(lat) / len(lat) if lat else None,
            # the split the combined numbers above conflate: time
            # queued before a worker picked the ticket vs time
            # actually executing (queue_wait + service = latency for
            # unbatched requests; batched members share the service
            # window, so the split is per-request exact either way)
            'queue_p50_s': self._pctile(qwaits, 0.50),
            'queue_p99_s': self._pctile(qwaits, 0.99),
            'queue_mean_s': sum(qwaits) / len(qwaits)
            if qwaits else None,
            'service_p50_s': self._pctile(stimes, 0.50),
            'service_p99_s': self._pctile(stimes, 0.99),
            'service_mean_s': sum(stimes) / len(stimes)
            if stimes else None,
            'rps': completed / wall,
            'wall_s': wall,
            'workers': len(self.meshes),
            'ndevices_per_worker': self.ndevices,
            'programs': len(self.programs),
            # the ingestion posture: how many completed requests
            # streamed a catalog, how many of those were served from
            # the on-device cache, and the fleet-wide cache counters
            # (the doctor's thrash verdict reads evictions vs hits)
            'ingest_requests': len(ingest_events),
            'ingest_cache_hits': sum(
                1 for e in ingest_events if e.get('cache_hit')),
            'ingest_gb': round(sum(
                float(e.get('bytes') or 0)
                for e in ingest_events) / 1e9, 6),
            'ingest_cache': cat,
            # the tier-1 integrity posture (docs/INTEGRITY.md):
            # shadowed runs, mismatches caught, and how many requests
            # recovered through the Supervisor's one integrity retry —
            # the doctor FAILs when mismatches outnumber recoveries
            'shadow_verified': shadow['verified'],
            'shadow_mismatch': shadow['mismatch'],
            'integrity_retried': sum(
                1 for r in results
                if r.event_count('integrity_retries')),
            'by_class': {k: {'n': len(v),
                             'p50_s': self._pctile(v, 0.50),
                             'p99_s': self._pctile(v, 0.99)}
                         for k, v in sorted(by_class.items())},
            # per-shape-class SLO burn verdicts (diagnostics/slo.py)
            'slo': self.slo.snapshot(),
        }
