"""Device-count-invariant random number generation.

The reference achieves rank-count invariance with MPIRandomState: a
chunked seed table so each global chunk draws from its own seed stream
regardless of which rank owns it (nbodykit/mpirng.py:5-136). Here the
same property is free: draws are functions of (seed, call-counter,
global shape) generated as global (sharded) arrays with jax's
counter-based threefry — values never depend on the device layout.

Each method call advances an internal counter (folded into the key), so
a sequence of calls reproduces exactly given the same seed and call
order — matching the stateful feel of numpy.random.RandomState that the
reference's catalog constructors rely on.
"""

import numpy as np
import jax
import jax.numpy as jnp

from .parallel.runtime import shard_leading


class DistributedRNG(object):
    """A stateful RandomState-like façade over jax.random producing
    global arrays of length ``size`` (+ itemshape)."""

    def __init__(self, seed, size, comm=None):
        self.seed = int(seed)
        self.size = int(size)
        self.comm = comm
        self._counter = 0

    def _next_key(self):
        key = jax.random.fold_in(jax.random.key(self.seed), self._counter)
        self._counter += 1
        return key

    def _shape(self, itemshape):
        if itemshape is None:
            return (self.size,)
        if np.isscalar(itemshape):
            itemshape = (itemshape,)
        return (self.size,) + tuple(itemshape)

    def _place(self, arr):
        from .parallel.runtime import mesh_size
        if self.comm is not None and mesh_size(self.comm) > 1 \
                and arr.shape[0] % mesh_size(self.comm) == 0:
            arr = shard_leading(self.comm, arr)
        return arr

    def uniform(self, low=0.0, high=1.0, itemshape=None, dtype='f8'):
        from .utils import working_dtype
        u = jax.random.uniform(self._next_key(), self._shape(itemshape),
                               dtype=working_dtype(dtype), minval=low,
                               maxval=high)
        return self._place(u)

    def normal(self, loc=0.0, scale=1.0, itemshape=None, dtype='f8'):
        from .utils import working_dtype
        g = jax.random.normal(self._next_key(), self._shape(itemshape),
                              dtype=working_dtype(dtype))
        return self._place(g * scale + loc)

    def poisson(self, lam, itemshape=None, dtype='i8'):
        lam = jnp.asarray(lam)
        shape = self._shape(itemshape)
        if lam.ndim > 0:
            shape = jnp.broadcast_shapes(shape, lam.shape)
        p = jax.random.poisson(self._next_key(), lam, shape=shape)
        from .utils import working_dtype                 # i8 -> i4
        return self._place(p.astype(working_dtype(dtype)))  # if x64 off

    def choice(self, choices, p=None, itemshape=None):
        choices = jnp.asarray(choices)
        c = jax.random.choice(self._next_key(), choices,
                              shape=self._shape(itemshape), p=p)
        return self._place(c)
