"""nbodykit-tpu: a TPU-native large-scale-structure analysis framework.

A ground-up re-design of the capabilities of bccp/nbodykit (reference:
/root/reference) for the JAX/XLA/TPU stack:

- distributed particle catalogs and 3-D density meshes are global
  ``jax.Array``s sharded over a ``jax.sharding.Mesh`` (slab decomposition),
  not MPI-rank-local numpy arrays;
- the distributed FFT (reference: pfft/pmesh) is local FFTs + in-graph
  ``lax.all_to_all`` transposes under ``jax.shard_map``;
- particle painting/readout (reference: pmesh C kernels) are fused
  scatter/gather kernels with halo exchange via ``lax.ppermute``;
- MPI collectives (reference: mpi4py) become XLA collectives inside jit;
- random numbers are device-count invariant by construction: every random
  draw is a function of (seed, global index) generated as a global sharded
  array (reference achieves this with MPIRandomState chunked seeding,
  nbodykit/mpirng.py:5).

The public API mirrors the capability surface inventoried in SURVEY.md §2:
catalogs, meshes, FFT-based spectra estimators, group finders, pair counting,
mock generation, cosmology, IO, and batch processing.
"""

import logging
import os
import time
from contextlib import contextmanager

__version__ = "0.1.0"

# ---------------------------------------------------------------------------
# global options (reference: nbodykit/__init__.py:22-25, set_options :215-256)
# ---------------------------------------------------------------------------

_default_options = {
    # dtype used for meshes created via to_mesh() unless overridden.
    # 'bf16' stores mesh buffers in bfloat16 (half the HBM of 'f4')
    # with f32-compensated deposit merges and immediate re-widening on
    # readout/FFT entry (docs/PERF.md "Halving the bytes"); 'auto'
    # consults the tune cache, falling back to 'f4'
    'mesh_dtype': 'f4',
    # all_to_all payload compression for the distributed FFT
    # (parallel/dfft.py, slab AND pencil drivers): 'none' sends the
    # f32 complex shards as-is; 'bf16' casts the payload
    # bfloat16-on-the-wire and re-widens to f32 immediately after the
    # collective; 'int16' sends an int16-quantized payload with
    # per-slab f32 scale factors carried alongside the shards. FFT
    # stages always COMPUTE f32 — only the wire bytes halve. 'auto'
    # consults the tune cache, falling back to 'none'
    'a2a_compress': 'none',
    # number of particles painted per chunk on the host-streaming path
    'paint_chunk_size': 1024 * 1024 * 16,
    # slack factor for fixed-capacity particle exchange buffers
    'exchange_slack': 1.25,
    # default resampler window
    'resampler': 'cic',
    # paint kernel: 'scatter' (chunked scatter-add), 'sort'
    # (scatter-free sort + segmented reduction), 'mxu'
    # (tile-bucketed batched-matmul deposit; see ops/paint.py) or
    # 'auto' (the measured winner from the tune cache for this
    # platform/shape — nbodykit_tpu.tune, docs/TUNE.md; a cold cache
    # falls back to 'scatter' with zero trial overhead)
    'paint_method': 'scatter',
    # bucket-capacity slack for the 'mxu' paint kernel
    'paint_bucket_slack': 2.0,
    # stable ordering engine for the mxu paint's bucketing: 'auto'
    # (radix counting sort on TPU, bitonic argsort elsewhere),
    # 'argsort', or 'radix' (ops/radix.py)
    'paint_order': 'auto',
    # deposit engine for the mxu paint: 'auto'/'xla' (one-hot
    # expansions via XLA) or 'pallas' (fused VMEM kernel,
    # ops/paint_pallas.py)
    'paint_deposit': 'auto',
    # replica-mesh count for the 'streams' paint kernel (the number of
    # independent scatter chains; each replica is a full mesh buffer —
    # memory_plan counts them against the HBM budget). 'auto' takes
    # the tune-cache winner, falling back to 4
    'paint_streams': 'auto',
    # single-device FFTs whose complex output exceeds this many bytes
    # run as slab-chunked per-axis passes (a single FFT op over a
    # multi-GB buffer exceeds TPU compiler limits; see parallel/dfft).
    # 0 disables chunking; 'auto' consults the tune cache
    # (nbodykit_tpu.tune) and falls back to 2**31 when cold.
    'fft_chunk_bytes': 2 ** 31,
    # distributed-FFT decomposition: 'slab' (1-D mesh, one P-way
    # all_to_all), 'pencil' (2-D Mesh(('x','y')), two smaller
    # transposes — inner over ICI, outer over DCN; parallel/dfft.py) or
    # 'auto' (the measured winner from the tune cache, keyed by device
    # count AND (Px, Py) factorization; cold cache falls back to
    # 'slab' at zero trial cost)
    'fft_decomp': 'slab',
    # explicit (Px, Py) factorization for the pencil path, as 'PXxPY'
    # (e.g. '4x2') or a tuple; None picks the most nearly square
    # factorization of the device count (runtime.default_pencil_factor)
    'fft_pencil': None,
    # rows per host chunk on the streaming ingestion path
    # (nbodykit_tpu.ingest, docs/INGEST.md): the window each
    # double-buffered device_put/paint step moves — the host never
    # holds more than two windows. 'auto' consults the tune cache
    # (keyed by the part-count shape class), falling back to 262144
    'ingest_chunk_rows': 'auto',
    # overlap H2D transfer of chunk i+1 with the paint of chunk i
    # (the double buffer). False serializes transfer-then-paint —
    # kept selectable for A/B measurement (bench --ingest)
    'ingest_overlap': True,
    # hard cap (bytes) on the on-device catalog cache per sub-mesh;
    # 'auto'/None defers entirely to memory_plan pricing at admission
    'ingest_cache_bytes': 'auto',
    # performance-database file for 'auto' option resolution and
    # nbodykit-tpu-tune (nbodykit_tpu.tune, docs/TUNE.md). None uses
    # the committed repo-root TUNE_CACHE.json; seeded from
    # $NBKIT_TUNE_CACHE so detached workers can be pointed elsewhere.
    'tune_cache': os.environ.get('NBKIT_TUNE_CACHE') or None,
    # telemetry sink: None disables; a path enables the span tracer +
    # crash-safe JSONL trace (nbodykit_tpu.diagnostics, docs/
    # OBSERVABILITY.md). Seeded from $NBKIT_DIAGNOSTICS so detached
    # workers (bench, multi-host) can be told to leave a post-mortem
    # trace without code changes.
    'diagnostics': os.environ.get('NBKIT_DIAGNOSTICS') or None,
    # deterministic fault injection (nbodykit_tpu.resilience.faults,
    # docs/RESILIENCE.md): 'point@N:action[,...]' fires a chosen
    # XlaRuntimeError (or SIGKILL) at the Nth call to a named fault
    # point. None disables. Seeded from $NBKIT_FAULTS so detached
    # workers (bench, multi-host) can be fault-injected without code
    # changes.
    'faults': os.environ.get('NBKIT_FAULTS') or None,
    # silent-data-corruption defense tier (nbodykit_tpu.resilience.
    # integrity, docs/INTEGRITY.md): 'off' (default — bit-identical to
    # a build without the integrity layer, zero added ops) or 'cheap'
    # (on-device invariants priced as near-free reductions: paint mass
    # conservation, Parseval brackets around the distributed FFTs,
    # NaN/Inf tripwires, fold-reduction checksums across every
    # all_to_all wire format). Seeded from $NBKIT_INTEGRITY so
    # detached workers can be armed without code changes.
    'integrity': os.environ.get('NBKIT_INTEGRITY') or 'off',
    # verify the per-physical-file byte-sum checksums bigfile columns
    # are written with on first read (io/bigfile.py); a mismatch
    # raises a structured ChecksumMismatch instead of silently
    # analyzing corrupt rows. False skips verification (bulk loads
    # where the caller audits out of band).
    'io_verify_checksums': True,
    # seconds a data_ref request is reserved for its cache-affine
    # serve worker before any idle worker may steal it (a steal pays
    # a cold re-ingest; docs/SERVING.md). 'auto' defers to
    # $NBKIT_DATA_STEAL_GRACE_S, else the AnalysisServer default
    # (1.0). Must be a non-negative finite number; 0 steals freely.
    # Resolved at server construction, validated there.
    'data_steal_grace_s': 'auto',
    # live telemetry export (nbodykit_tpu.diagnostics.export,
    # docs/OBSERVABILITY.md): an integer TCP port starts a
    # bispectrum estimator selection: 'fft' (Scoccimarro filtered-field
    # triangle counts, low k), 'direct' (blocked pairwise mode sums on
    # the MXU, high k), or 'auto' — consult the tune cache for the
    # measured crossover of this platform/shape, falling back to 'fft'
    'bspec_method': 'auto',
    # tile edge of the direct path's dense (tile x tile) phase blocks
    # (ops/pairblock.py). 'auto' consults the tune cache (raced inside
    # the bspec space), falling back to 1024
    'pairblock_tile': 'auto',
    # zero-dependency background HTTP thread serving the metrics
    # registry and SLO state as Prometheus text (/metrics), JSON
    # snapshots (/metrics.json, /slo) and the flight-recorder ring
    # (/flight). 0 binds an ephemeral port (the exporter reports the
    # real one); None disables. Seeded from $NBKIT_TELEMETRY_PORT so
    # detached workers can be scraped without code changes.
    'telemetry_port': os.environ.get('NBKIT_TELEMETRY_PORT') or None,
}


class _Options(object):
    """Thread-aware options mapping.

    The main thread reads/writes one shared dict; any other thread
    (e.g. a TaskManager worker farming tasks to device sub-meshes,
    batch.py) gets its own copy seeded from the main thread's values at
    first use — so concurrent tasks using ``set_options`` cannot race
    each other or corrupt the process-wide defaults.
    """

    def __init__(self, defaults):
        import threading
        self._threading = threading
        self._main = dict(defaults)
        self._tls = threading.local()

    def _cur(self):
        if self._threading.current_thread() is \
                self._threading.main_thread():
            return self._main
        d = getattr(self._tls, 'd', None)
        if d is None:
            d = dict(self._main)
            self._tls.d = d
        return d

    def __getitem__(self, key):
        return self._cur()[key]

    def __setitem__(self, key, value):
        self._cur()[key] = value

    def __contains__(self, key):
        return key in self._cur()

    def __iter__(self):
        return iter(self._cur())

    def keys(self):
        return self._cur().keys()

    def copy(self):
        return dict(self._cur())

    def update(self, other):
        self._cur().update(other)

    def clear(self):
        self._cur().clear()


_global_options = _Options(_default_options)


class set_options(object):
    """Context manager / callable to set global framework options.

    Mirrors the semantics of the reference's ``nbodykit.set_options``
    (nbodykit/__init__.py:215-256): usable both as a plain call and as a
    ``with`` block that restores the previous values on exit.

    Parameters
    ----------
    mesh_dtype : str
        default dtype of meshes created by ``to_mesh``: 'f4' (the
        default), 'f8' (demoted to f4 when x64 is off), 'bf16' (mesh
        buffers stored bfloat16 at half the f4 HBM footprint — paint
        deposits into bf16 replica meshes with an f32 compensated
        two-sum merge, readout and FFT entry re-widen to f32
        immediately; accuracy budget asserted in tests/
        test_precision.py), or 'auto' (the tune-cache winner for this
        platform/shape, falling back to 'f4').
    a2a_compress : str
        distributed-FFT ``all_to_all`` payload compression
        (parallel/dfft.py, both slab and pencil): 'none' (default),
        'bf16' (bfloat16 on the wire, f32 out — the payload is
        re-widened immediately after the collective), 'int16'
        (quantized payload + per-slab f32 scale factors riding
        alongside), or 'auto' (tune-cache winner, falling back to
        'none').  FFT butterflies always compute f32; only the wire
        bytes halve.
    paint_chunk_size : int
        number of particles processed per chunk when streaming from host.
    exchange_slack : float
        capacity slack factor for the fixed-capacity particle exchange.
    resampler : str
        default window: 'nnb', 'cic', 'tsc', 'pcs'.
    paint_method : str
        'scatter', 'sort', 'segsum', 'streams', 'mxu' — the local
        deposit kernel — or 'auto': the measured winner recorded in
        the tune cache for this platform/device/shape
        (:mod:`nbodykit_tpu.tune`, docs/TUNE.md); a cold cache
        resolves to 'scatter' at zero trial cost.
    paint_bucket_slack : float
        bucket-capacity slack factor for the 'mxu' paint kernel.
    paint_streams : int or 'auto'
        replica-mesh count for the 'streams' paint kernel — the number
        of independent scatter chains the s^3 window-offset streams
        are dealt onto (each replica is a full mesh buffer, counted by
        ``memory_plan``); 'auto' consults the tune cache, falling
        back to 4.
    fft_chunk_bytes : int or 'auto'
        single-device FFTs with complex output larger than this run as
        slab-chunked per-axis passes (0 disables); 'auto' consults the
        tune cache, falling back to 2**31 when cold.
    fft_decomp : str
        distributed-FFT decomposition: 'slab' (one P-way all_to_all
        over the 1-D mesh), 'pencil' (two smaller transposes over a
        2-D ``Mesh(('x','y'))`` — see parallel/dfft.py and
        docs/PERF.md "Slab vs pencil"), or 'auto' (the tune-cache
        winner for this platform, device count and (Px, Py)
        factorization; a cold cache resolves to 'slab').
    fft_pencil : str, tuple or None
        explicit (Px, Py) device factorization for the pencil path
        ('4x2' or ``(4, 2)``); None picks the most nearly square
        factorization of the device count.
    tune_cache : str or None
        path of the performance database consulted by 'auto' options
        and written by ``nbodykit-tpu-tune``; None (the default) uses
        the committed repo-root ``TUNE_CACHE.json``.  Seeded from
        ``$NBKIT_TUNE_CACHE``.
    diagnostics : str or None
        path of the telemetry sink (a directory, or a ``*.jsonl``
        file): enables the span tracer + metrics of
        :mod:`nbodykit_tpu.diagnostics` with crash-safe JSONL output.
        None (the default) disables all tracing at zero cost.
    faults : str or None
        deterministic fault-injection spec
        (``'point@N:action[,...]'``) for
        :mod:`nbodykit_tpu.resilience.faults`; actions are
        ``unavailable`` / ``resource_exhausted`` / ``deadline`` /
        ``internal`` / ``kill`` / ``corrupt[:bits]`` (flip payload
        bits at a named data-injection point — the testable stand-in
        for real silent data corruption).  None (the default)
        disables.
    integrity : str
        silent-data-corruption defense
        (:mod:`nbodykit_tpu.resilience.integrity`, docs/INTEGRITY.md):
        'off' (the default — bit-identical results and zero added
        ops) or 'cheap' (tier-0 on-device invariants: exact paint
        mass conservation, Parseval checks bracketing the distributed
        FFTs, NaN/Inf tripwires on mesh-sized intermediates, and
        fold-reduction checksums across every ``all_to_all`` payload
        including the bf16/int16 compressed wire formats).  A
        violation raises a classified
        :class:`~nbodykit_tpu.resilience.IntegrityError`; the
        Supervisor retries it exactly once.
    io_verify_checksums : bool
        verify each bigfile physical file's stored 32-bit byte-sum
        checksum the first time the file is read
        (:mod:`nbodykit_tpu.io.bigfile`); a mismatch raises
        :class:`~nbodykit_tpu.io.bigfile.ChecksumMismatch` with the
        file, column and both sums.  True by default; False opts out.
    data_steal_grace_s : float or 'auto'
        seconds a ``data_ref`` request stays reserved for its
        cache-affine serve worker before any idle worker may steal it
        (stealing pays a cold catalog re-ingest; docs/SERVING.md).
        'auto' (the default) defers to ``$NBKIT_DATA_STEAL_GRACE_S``,
        else 1.0.  Must be non-negative and finite (0 disables the
        grace window entirely); validated when an
        :class:`~nbodykit_tpu.serve.AnalysisServer` is constructed.
    telemetry_port : int or None
        TCP port for the live telemetry exporter
        (:mod:`nbodykit_tpu.diagnostics.export`): a background HTTP
        thread serving the metrics registry as Prometheus text
        (``/metrics``), JSON snapshots (``/metrics.json``, ``/slo``)
        and the flight-recorder ring (``/flight``).  0 binds an
        ephemeral port; None (the default) disables.  Seeded from
        ``$NBKIT_TELEMETRY_PORT``.  The serve/region front doors
        start the exporter on construction when this is set.
    """

    def __init__(self, **kwargs):
        self.old = _global_options.copy()
        for key in kwargs:
            if key not in _global_options:
                raise KeyError('invalid option: %r (valid: %s)'
                               % (key, sorted(_global_options)))
        _global_options.update(kwargs)

    def __enter__(self):
        return self

    def __exit__(self, *args):
        _global_options.clear()
        _global_options.update(self.old)


@contextmanager
def option_scope(**overrides):
    """Request-scoped option override that CANNOT leak.

    ``set_options`` used as a context manager restores the values it
    saved — but a bare ``set_options(...)`` call inside the block (or
    inside library code the block runs) survives it.  On the main
    thread that is a deliberate feature; on a long-lived worker thread
    that is a cross-tenant leak: ``_Options`` gives every non-main
    thread a persistent thread-local dict, so whatever request N
    leaves behind becomes request N+1's ambient configuration when the
    pool reuses the thread.

    This context snapshots the calling thread's FULL option dict on
    entry and restores it wholesale on exit, so nothing set inside the
    scope — by ``overrides``, by nested ``set_options``, by a
    degradation-ladder rung — outlives it.  The serving layer
    (:mod:`nbodykit_tpu.serve`) wraps every request in one.
    """
    for key in overrides:
        if key not in _global_options:
            raise KeyError('invalid option: %r (valid: %s)'
                           % (key, sorted(_global_options)))
    saved = _global_options.copy()
    _global_options.update(overrides)
    try:
        yield
    finally:
        _global_options.clear()
        _global_options.update(saved)


# ---------------------------------------------------------------------------
# logging (reference: nbodykit/__init__.py:258-300)
# ---------------------------------------------------------------------------

_logging_handler = None


def setup_logging(log_level="info"):
    """Set up logging with elapsed-wall-clock-stamped records.

    The reference formats records as ``[ elapsed ] rank: msg``
    (nbodykit/__init__.py:269-300); here there is a single controller
    process, so records are ``[ elapsed ] level: msg``.
    """
    levels = {
        "info": logging.INFO,
        "debug": logging.DEBUG,
        "warning": logging.WARNING,
        "error": logging.ERROR,
    }

    logger = logging.getLogger()
    t0 = time.time()

    class Formatter(logging.Formatter):
        def format(self, record):
            s1 = ('[ %09.2f ] ' % (time.time() - t0))
            return s1 + logging.Formatter.format(self, record)

    fmt = Formatter(fmt='%(levelname)s %(name)s: %(message)s')

    global _logging_handler
    if _logging_handler is None:
        _logging_handler = logging.StreamHandler()
        logger.addHandler(_logging_handler)

    _logging_handler.setFormatter(fmt)
    logger.setLevel(levels[log_level])


@contextmanager
def timer(name, logger=None):
    """Context manager timing a named phase (reference: utils.timer,
    nbodykit/utils.py:491).

    Routed through :mod:`nbodykit_tpu.diagnostics`: when the
    ``diagnostics`` option is set, every existing ``timer(...)`` call
    site also emits a crash-safe ``timer.<name>`` span with zero
    caller changes (no-op otherwise)."""
    from .diagnostics import span
    t0 = time.time()
    with span('timer.%s' % name):
        yield
    dt = time.time() - t0
    msg = "%s: %.3f s" % (name, dt)
    if logger is not None:
        logger.info(msg)
    else:
        logging.getLogger('timer').info(msg)


from . import _jax_compat  # noqa: E402,F401  (backfills jax.shard_map on old jax)
from .parallel.runtime import CurrentMesh, use_mesh, cpu_mesh, tpu_mesh  # noqa: E402,F401


@contextmanager
def profile(path='/tmp/nbodykit-tpu-trace', host=False):
    """Capture a jax profiler trace of the enclosed block (SURVEY.md §5
    'tracing': the reference has wall-clock phase logging only; here the
    full XLA timeline lands in TensorBoard format at ``path``).

    Also emits a ``profile`` span (with the trace path) when the
    ``diagnostics`` option is set, so the XLA capture window is
    locatable inside the span timeline."""
    import jax
    from .diagnostics import span
    jax.profiler.start_trace(path)
    try:
        with span('profile', path=path, host=bool(host)):
            yield path
    finally:
        jax.profiler.stop_trace()
