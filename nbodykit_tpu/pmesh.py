"""ParticleMesh: the TPU-native replacement for ``pmesh.pm.ParticleMesh``.

The reference builds everything on pmesh's MPI ParticleMesh/RealField/
ComplexField (created at nbodykit/base/mesh.py:50, consumed throughout).
Here the same capability surface is provided over JAX:

- fields are *global* ``jax.Array``s (slab-sharded over a 1-D device mesh
  when one is active), not rank-local blocks;
- ``r2c``/``c2r`` use :mod:`nbodykit_tpu.parallel.dfft` (local FFTs +
  all_to_all), with pmesh's forward-normalized convention
  (``c2r(r2c(x)) == x``; r2c divides by Nmesh^3);
- complex fields are hermitian-compressed and *transposed*: global shape
  (N1, N0, N2//2+1), leading axis = ky (see dfft.py docstring);
- ``paint``/``readout`` route particles to slab owners with a fixed-
  capacity all_to_all, then scatter/gather on halo-extended blocks
  (parallel/halo.py), replacing pmesh.domain decompose/exchange
  (reference call sites: nbodykit/source/mesh/catalog.py:271-296);
- ``generate_whitenoise`` draws a device-count-invariant unit-variance
  complex field (reference: pm.generate_whitenoise at mockmaker.py:83).

Everything returned is a plain jnp array; the RealField/ComplexField
wrappers in :mod:`nbodykit_tpu.base.mesh` add attrs/convenience methods.
"""

import logging
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from . import _global_options
from .diagnostics import counter, current_tracer, histogram, \
    install_compile_telemetry, span, \
    trace_state_clean
from .parallel.runtime import AXIS, CurrentMesh, mesh_size, shard_leading
from .parallel import dfft
from .parallel.halo import halo_add, halo_fill
from .parallel.exchange import exchange_by_dest
from .ops.window import window_support
from .ops.paint import (paint_local, paint_local_sorted,
                        paint_local_segsum, paint_local_streams,
                        paint_local_mxu, readout_local)

# compile telemetry for the paint/FFT entry points below: XLA compiles
# and compilation-cache hits/misses land in the metric registry
install_compile_telemetry()


def _triplet(x, dtype):
    a = np.empty(3, dtype=dtype)
    a[:] = x
    return a


class ParticleMesh(object):
    """Geometry + parallel layout descriptor for 3-D particle-mesh fields.

    Parameters
    ----------
    Nmesh : int or 3-vector — cells per side
    BoxSize : float or 3-vector — box side length(s)
    dtype : mesh dtype ('f4' or 'f8')
    comm : jax.sharding.Mesh or None — the device mesh (defaults to the
        ambient :class:`~nbodykit_tpu.parallel.runtime.CurrentMesh`)
    """

    logger = logging.getLogger('ParticleMesh')

    def __init__(self, Nmesh, BoxSize, dtype='f4', comm=None):
        self.Nmesh = _triplet(Nmesh, 'i8')
        self.BoxSize = _triplet(BoxSize, 'f8')
        from .utils import is_narrow_float, mesh_storage_dtype
        # canonicalize up front: an f8 mesh with x64 disabled (the TPU
        # reality) IS an f4 mesh — deciding here keeps every kernel
        # below free of per-callsite truncation warnings.  'bf16' is a
        # STORAGE dtype only: mesh buffers are bfloat16 (half the f4
        # HBM) while everything computed over them — deposit weights,
        # FFT butterflies, readout gathers — runs in ``compute_dtype``
        # (f32) and narrows back at the buffer boundary (docs/PERF.md
        # "Halving the bytes"; accuracy gate in tests/test_precision.py)
        self.dtype = mesh_storage_dtype(dtype)
        self.compute_dtype = np.dtype('f4') \
            if is_narrow_float(self.dtype) else self.dtype
        self.comm = CurrentMesh.resolve(comm)
        self.nproc = mesh_size(self.comm)
        if int(self.Nmesh[0]) % self.nproc or int(self.Nmesh[1]) % self.nproc:
            raise ValueError("Nmesh[0], Nmesh[1] must be divisible by the "
                             "%d-device mesh" % self.nproc)
        self._plan = dfft.dist_fft_plan(self.Nmesh, self.comm)

    # -- shapes -----------------------------------------------------------

    @property
    def shape_real(self):
        return tuple(int(n) for n in self.Nmesh)

    @property
    def shape_complex(self):
        """Transposed, hermitian-compressed layout (ky, kx, kz)."""
        N0, N1, N2 = (int(n) for n in self.Nmesh)
        return (N1, N0, N2 // 2 + 1)

    @property
    def Ntot(self):
        return int(np.prod(self.Nmesh))

    @property
    def cellsize(self):
        return self.BoxSize / self.Nmesh

    def __eq__(self, other):
        return (isinstance(other, ParticleMesh)
                and np.array_equal(self.Nmesh, other.Nmesh)
                and np.array_equal(self.BoxSize, other.BoxSize))

    # -- field creation ---------------------------------------------------

    def sharding(self, ndim=3):
        if self.comm is None:
            return None
        return NamedSharding(self.comm, P(*((AXIS,) + (None,) * (ndim - 1))))

    def create(self, type='real', value=0.):
        """A zero (or constant) field of the requested type."""
        if type == 'real':
            shape, dtype = self.shape_real, self.dtype
        elif type in ('complex', 'transposedcomplex'):
            shape = self.shape_complex
            dtype = jnp.complex64 if self.dtype.itemsize <= 4 \
                else jnp.complex128
        else:
            raise ValueError("field type must be 'real' or 'complex'")
        arr = jnp.full(shape, value, dtype=dtype)
        if self.comm is not None:
            arr = jax.device_put(arr, self.sharding())
        return arr

    # -- FFT --------------------------------------------------------------

    def r2c(self, real):
        """Forward real-to-complex FFT, forward-normalized (pmesh
        convention: divides by Nmesh^3 so the result is 'dimensionless').

        Narrow-storage (bf16) meshes re-widen to f32 at this boundary:
        the FFT stages always compute f32 — storage never reaches a
        butterfly (wire-level compression is the separate
        ``a2a_compress`` knob in parallel/dfft.py)."""
        from .utils import is_narrow_float
        real = jnp.asarray(real)
        if is_narrow_float(real.dtype):
            real = real.astype(jnp.float32)
        return self._plan.r2c(real) * (1.0 / self.Ntot)

    def c2r(self, cplx):
        """Inverse transform of :meth:`r2c` (unnormalized inverse since the
        forward carried the 1/N^3)."""
        return self._plan.c2r(cplx * self.Ntot).astype(self.dtype)

    # -- coordinates ------------------------------------------------------

    def x_list(self, dtype=None):
        """Broadcastable real-space coordinate arrays [x, y, z] for the
        (N0, N1, N2) real layout: x_i = index * cellsize_i, in [0, L)."""
        from .utils import working_dtype
        # coordinates are compute-dtype: a bf16 storage mesh still gets
        # f32 coordinate arrays (8 mantissa bits cannot index a lattice)
        dtype = working_dtype(dtype) if dtype is not None \
            else np.dtype(self.compute_dtype)
        out = []
        for ax, (n, h) in enumerate(zip(self.Nmesh, self.cellsize)):
            shape = [1, 1, 1]
            shape[ax] = int(n)
            out.append((jnp.arange(int(n), dtype=dtype)
                        * jnp.asarray(h, dtype)).reshape(shape))
        return out

    def k_list(self, dtype=None, circular=False, full=False):
        """Broadcastable k-coordinate arrays [kx, ky, kz] for the
        *transposed* complex layout (axis0=ky, axis1=kx, axis2=kz).

        ``circular=True`` gives w_i = k_i * BoxSize_i / Nmesh_i in
        [-pi, pi) (the reference's 'circular' apply kind,
        nbodykit/base/mesh.py:132-145). ``full=True`` gives the
        uncompressed kz axis (c2c layout) instead of the rfft half.
        """
        from .utils import working_dtype
        dtype = working_dtype(dtype) if dtype is not None else (
            jnp.float32 if self.dtype.itemsize <= 4
            else working_dtype('f8'))
        N0, N1, N2 = (int(n) for n in self.Nmesh)
        L = self.BoxSize

        def freq(n, L_i, r2c_axis=False):
            if r2c_axis and not full:
                j = jnp.arange(n // 2 + 1, dtype=dtype)
            else:
                j = jnp.fft.fftfreq(n, d=1.0 / n).astype(dtype)
            if circular:
                return j * jnp.asarray(2 * np.pi / n, dtype)
            return j * jnp.asarray(2 * np.pi / L_i, dtype)

        kx = freq(N0, L[0]).reshape(1, N0, 1)
        ky = freq(N1, L[1]).reshape(N1, 1, 1)
        nz = N2 if full else N2 // 2 + 1
        kz = freq(N2, L[2], r2c_axis=True).reshape(1, 1, nz)
        return [kx, ky, kz]

    def i_list_complex(self):
        """Broadcastable integer mode-index arrays [ix, iy, iz] (signed,
        fftfreq convention) for the transposed complex layout."""
        N0, N1, N2 = (int(n) for n in self.Nmesh)
        ix = jnp.fft.fftfreq(N0, d=1.0 / N0).astype(jnp.int32).reshape(1, N0, 1)
        iy = jnp.fft.fftfreq(N1, d=1.0 / N1).astype(jnp.int32).reshape(N1, 1, 1)
        iz = jnp.arange(N2 // 2 + 1, dtype=jnp.int32).reshape(1, 1, -1)
        return [ix, iy, iz]

    def hermitian_weights(self, dtype=jnp.float32):
        """Double-count weights for the compressed kz half-space: weight 2
        for 0 < kz < Nyquist, weight 1 on the kz=0 and Nyquist planes
        (reference: nbodykit/meshtools.py:188-215)."""
        from .utils import working_dtype
        N2 = int(self.Nmesh[2])
        nz = N2 // 2 + 1
        iz = jnp.arange(nz)
        w = jnp.where((iz > 0) & ~((N2 % 2 == 0) & (iz == N2 // 2)), 2.0, 1.0)
        return w.astype(working_dtype(dtype)).reshape(1, 1, nz)

    # -- paint / readout --------------------------------------------------

    def _to_cell_units(self, pos):
        scale = jnp.asarray(self.Nmesh / self.BoxSize, pos.dtype)
        return pos * scale

    def _check_halo(self, h):
        """Validate halo width against the per-device slab height; the
        single-hop ppermute halo exchange requires support <= N0/P."""
        n0 = int(self.Nmesh[0]) // self.nproc
        if h > n0:
            raise ValueError(
                "resampler support %d exceeds the per-device slab height "
                "%d (= Nmesh[0]=%d / %d devices); use a larger Nmesh, "
                "fewer devices, or a narrower window"
                % (h, n0, int(self.Nmesh[0]), self.nproc))
        return n0

    def _route_dest(self, cpos):
        """Slab owner per particle (cpos in cell units, shift already
        applied) — THE routing rule, shared by paint/readout and the
        counted-capacity pass so they cannot drift apart."""
        N0 = int(self.Nmesh[0])
        n0 = N0 // self.nproc
        cell = jnp.mod(jnp.floor(cpos[:, 0]).astype(jnp.int32), N0)
        return cell // n0

    def _paint_config(self, npart):
        """The effective paint-kernel configuration for one call:
        current options with every ``'auto'`` resolved through the
        tune cache (:mod:`nbodykit_tpu.tune` — measured winner for
        this platform/device-count/shape when one exists, today's
        defaults otherwise, zero trial overhead either way)."""
        from .tune.resolve import resolve_paint
        return resolve_paint(nmesh=int(self.Nmesh[0]), npart=int(npart),
                             dtype=self.dtype, nproc=self.nproc)

    def exchange_capacity(self, pos, slack=1.05, shift=0.0):
        """Two-pass counted exchange, pass 1 (run EAGERLY): the exact
        per-(src,dst) routing count for these positions, with slack.
        ``slack='auto'`` consults the tune cache (exchange op) and
        falls back to 1.05 when cold.

        Pass the result as ``capacity=`` to a *traced* :meth:`paint` /
        :meth:`readout` (with ``return_dropped=True``) so the
        all_to_all buffers are counted-size (~N/P^2) instead of the
        always-sufficient ceil(N/P) — the difference between fitting
        a 2048^3 mesh next to a 1e9-particle exchange and OOM (see
        :func:`memory_plan` and parallel/exchange.py).

        ``shift`` must match the paint's (interlaced painting routes by
        the half-cell-shifted grid; take the max of the capacities at
        shift 0 and 0.5 for an interlaced pair of paints).
        """
        from .parallel.exchange import auto_capacity
        if self.nproc == 1:
            return int(pos.shape[0])
        if slack == 'auto':
            from .tune.resolve import resolve_exchange_slack
            slack = resolve_exchange_slack(npart=int(pos.shape[0]),
                                           nproc=self.nproc)
        dest = self._route_dest(self._to_cell_units(pos) - shift)
        return auto_capacity(dest, self.nproc, slack=slack)

    def paint(self, pos, mass=1.0, resampler=None, out=None, shift=0.0,
              capacity=None, return_dropped=False):
        """Scatter particles onto the mesh; returns a real field.

        Parameters
        ----------
        pos : (N, 3) positions in box units (global array; sharded on axis
            0 when a device mesh is active)
        mass : scalar or (N,) weights; slots with mass 0 are inert
        shift : float, cell units — paint onto a half-cell-shifted grid
            (used by interlacing, reference source/mesh/catalog.py:292)
        capacity : per-(src,dst) exchange capacity; default derived from
            particle count and the 'exchange_slack' option.
        return_dropped : also return the exchange-overflow count so
            traced callers can check it after the step.

        Overflow contract (reference analog: the paint chunk backoff
        loop, nbodykit/source/mesh/catalog.py:275-315): with the default
        capacity, overflow is impossible (exact bound eagerly, ceil
        bound under trace). An explicit ``capacity`` is retried eagerly
        with doubled capacity until nothing drops; under a trace the
        check cannot branch, so ``return_dropped=True`` is REQUIRED —
        silent particle loss is never possible.

        Diagnostics (docs/OBSERVABILITY.md): eager calls with the
        ``diagnostics`` option set emit a ``paint`` span and record the
        per-method throughput histogram ``paint.<method>.mpart_per_s``.
        The result is synced (``block_until_ready``) inside the span so
        the throughput is real work, not dispatch — enabled-mode only;
        the disabled path is byte-identical to the undiagnosed one.

        Dropped-deposit contract for ``paint_method='mxu'``: the mxu
        kernel's slack-sized tile buckets CAN overflow. Eagerly the
        overflow self-heals — each retry of the slack-backoff ladder
        first bumps the process-wide ``paint.dropped`` counter and
        emits a ``paint.dropped`` trace event (count + failing slack),
        so no loss is silent even though the final mesh is exact.
        Under a trace the backoff cannot branch, so
        ``return_dropped=True`` is REQUIRED (enforced above): the
        traced path's ONLY overflow signal is the returned count —
        counters and events cannot fire inside jit — and a caller who
        ignores it has lost deposits with no trace-side record.
        """
        if current_tracer() is None or not trace_state_clean():
            return self._paint_impl(pos, mass, resampler, out, shift,
                                    capacity, return_dropped)
        npart = int(pos.shape[0])
        # the RESOLVED kernel labels the span/histograms — with
        # paint_method='auto' the trace must show which kernel ran,
        # not the sentinel
        method = self._paint_config(npart)['paint_method']
        t0 = time.perf_counter()
        with span('paint', method=method, npart=npart,
                  nproc=self.nproc,
                  resampler=resampler or _global_options['resampler'],
                  nmesh=int(self.Nmesh[0])):
            res = self._paint_impl(pos, mass, resampler, out, shift,
                                   capacity, return_dropped)
            jax.block_until_ready(res)
        dt = max(time.perf_counter() - t0, 1e-9)
        histogram('paint.%s.wall_s' % method).observe(dt)
        histogram('paint.%s.mpart_per_s' % method).observe(
            npart / dt / 1e6)
        return res

    def _paint_impl(self, pos, mass, resampler, out, shift, capacity,
                    return_dropped):
        resampler = resampler or _global_options['resampler']
        h = window_support(resampler)
        N0, N1, N2 = self.shape_real
        cpos = self._to_cell_units(pos) - shift
        npart = pos.shape[0]
        # weights are COMPUTE dtype: with bf16 storage the deposit
        # terms stay f32 and only the mesh buffers narrow (the streams
        # kernel's replica meshes, via storage_dtype below, plus the
        # final field cast at the exit)
        massa = jnp.broadcast_to(
            jnp.asarray(mass, self.compute_dtype), (npart,))
        # 'auto' options resolve through the tune cache here, at
        # dispatch time (cold cache -> today's defaults, no trials)
        pcfg = self._paint_config(npart)
        chunk = pcfg['paint_chunk_size']

        pm_method = pcfg['paint_method']
        traced = isinstance(cpos, jax.core.Tracer)
        # tier-0 integrity posture + chaos injection resolve here, at
        # dispatch: both are eager-only (a data-dependent raise cannot
        # live under trace) and integrity='off' takes the exact same
        # code path as before — zero added ops, bit-identical fields
        cbits = 0
        chk = False
        if not traced:
            from .resilience.faults import corrupt_spec
            from .resilience.integrity import checks_enabled
            cbits = corrupt_spec('paint.accum')
            chk = checks_enabled()
        if traced and pm_method == 'mxu' and not return_dropped \
                and pcfg['source'] != 'explicit':
            # a tune-cache winner must not impose the traced-mxu
            # overflow contract (return_dropped) on a caller who asked
            # for 'auto': fall back to the contract-free scatter
            # kernel for this call; only an EXPLICIT 'mxu' raises below
            pm_method = 'scatter'
        if traced and pm_method == 'mxu' and not return_dropped:
            # same contract as an explicit exchange capacity: the mxu
            # bucket capacity is slack-sized, not provably sufficient,
            # and under a trace the eager backoff cannot run — silent
            # particle loss must be impossible, so the caller has to
            # receive (and check) the dropped count
            raise ValueError(
                "paint_method='mxu' inside jit requires "
                "return_dropped=True: bucket overflow cannot retry "
                "under a trace, so the dropped count must be checked "
                "after the step (or paint eagerly / use "
                "paint_method='scatter')")

        def make_kernel(mxu_slack):
            """All kernels return (block, overflow); only mxu can
            actually overflow (bucket capacity)."""
            if pm_method == 'sort':
                def kern(*a, **kw):
                    return (paint_local_sorted(*a, **kw),
                            jnp.zeros((), jnp.int32))
            elif pm_method == 'segsum':
                order = pcfg['paint_order']

                def kern(*a, **kw):
                    return (paint_local_segsum(*a, order_method=order,
                                               **kw),
                            jnp.zeros((), jnp.int32))
            elif pm_method == 'streams':
                nstreams = pcfg['paint_streams']
                sdt = self.dtype

                def kern(*a, **kw):
                    return (paint_local_streams(*a, streams=nstreams,
                                                chunk=chunk,
                                                storage_dtype=sdt,
                                                **kw),
                            jnp.zeros((), jnp.int32))
            elif pm_method == 'mxu':
                order = pcfg['paint_order']
                dep = pcfg['paint_deposit']

                def kern(*a, **kw):
                    return paint_local_mxu(*a, slack=mxu_slack,
                                           return_overflow=True,
                                           order_method=order,
                                           deposit=dep, **kw)
            else:
                def kern(*a, **kw):
                    return (paint_local(*a, chunk=chunk, **kw),
                            jnp.zeros((), jnp.int32))
            return kern

        mxu_slack = _global_options['paint_bucket_slack']
        if self.nproc == 1:
            block, over = make_kernel(mxu_slack)(
                cpos, massa, self.shape_real, resampler=resampler,
                period=self.shape_real, origin=0)
            # eager mxu bucket-overflow backoff, mirroring the exchange
            # retry contract (traced callers see the count via
            # return_dropped)
            while not traced and int(over) > 0 and mxu_slack < 1e6:
                self._note_dropped(int(over), mxu_slack)
                mxu_slack *= 4
                self.logger.info(
                    "mxu paint bucket overflow (%d dropped); retrying "
                    "with slack=%g" % (int(over), mxu_slack))
                block, over = make_kernel(mxu_slack)(
                    cpos, massa, self.shape_real, resampler=resampler,
                    period=self.shape_real, origin=0)
            # kernels return compute dtype; widen any caller-held
            # accumulator before adding (never mix widths on a
            # mesh-sized operand) and narrow once at the exit
            if cbits:
                block = self._corrupt_accum(block, cbits)
            if out is not None:
                block = block + jnp.asarray(out).astype(block.dtype)
            if chk:
                self._verify_mass(block, massa, out, h, npart)
            out = block.astype(self.dtype)
            if return_dropped:
                return out, over
            return out

        n0 = self._check_halo(h)
        dest = self._route_dest(cpos)
        self._check_overflow_contract(capacity, traced, return_dropped)
        nproc = self.nproc

        def make_local(kernel):
            def local(cpos_l, mass_l):
                d = jax.lax.axis_index(AXIS)
                origin = d * n0 - h
                ext, over = kernel(cpos_l, mass_l,
                                   (n0 + 2 * h, N1, N2),
                                   resampler=resampler,
                                   period=(N0, N1, N2), origin=origin)
                return halo_add(ext, h, nproc), jax.lax.psum(over, AXIS)
            return local

        def attempt(cap, slack_val=None):
            kernel = make_kernel(slack_val if slack_val is not None
                                 else mxu_slack)
            recv, valid, dropped = exchange_by_dest(
                dest, [cpos, massa], self.comm, cap)
            cpos_r, mass_r = recv
            mass_r = jnp.where(valid, mass_r,
                               0.0).astype(self.compute_dtype)
            block, over = jax.shard_map(
                make_local(kernel), mesh=self.comm,
                in_specs=(P(AXIS, None), P(AXIS)),
                out_specs=(P(AXIS, None, None), P()))(cpos_r, mass_r)
            return block, dropped, over

        block, dropped, over = attempt(capacity)
        if not traced and capacity is not None and int(dropped) > 0:
            # eager exchange-capacity backoff (reference:
            # source/mesh/catalog.py:275-315), keeping all three
            # outputs from the final attempt
            cap_max = -(-npart // self.nproc) + 8
            while int(dropped) > 0 and capacity < cap_max:
                capacity = min(2 * capacity, cap_max)
                self.logger.info(
                    "exchange overflow (%d dropped); retrying with "
                    "capacity=%d" % (int(dropped), capacity))
                block, dropped, over = attempt(capacity)
            if int(dropped) > 0:
                # NBK103 (baselined, audited): this raise sits between
                # collective stages, but `dropped` is the
                # globally-summed overflow count — every rank computes
                # the same value and raises together, so the exception
                # path is rank-uniform by construction
                raise RuntimeError(
                    "particle exchange still overflowing at the "
                    "maximal capacity %d — this should be impossible"
                    % capacity)
        while not traced and int(over) > 0 and mxu_slack < 1e6:
            self._note_dropped(int(over), mxu_slack)
            mxu_slack *= 4
            self.logger.info(
                "mxu paint bucket overflow (%d dropped); retrying "
                "with slack=%g" % (int(over), mxu_slack))
            block, dropped, over = attempt(capacity, mxu_slack)
        # same merge-then-narrow contract as the single-device exit:
        # the halo_add ran in compute dtype inside the shard_map, the
        # storage cast happens exactly once, here
        if cbits:
            block = self._corrupt_accum(block, cbits)
        if out is not None:
            block = block + jnp.asarray(out).astype(block.dtype)
        if chk:
            self._verify_mass(block, massa, out, h, npart)
        out = block.astype(self.dtype)
        if return_dropped:
            return out, dropped + over
        return out

    def _corrupt_accum(self, block, bits):
        """Chaos-matrix injection for the ``paint.accum`` point: flip
        the top ``bits`` bits of one accumulated cell (before the
        merge, so the mass guard — not the injector — must catch it).
        Active regardless of the integrity mode: with checks off the
        corruption flows through silently, which IS the documented
        blind spot the tier exists to close."""
        from .resilience.integrity import corrupt_real
        return corrupt_real(block, bits)

    def _verify_mass(self, block, massa, prior, h, npart):
        """Tier-0 mass-conservation guard (resilience/integrity.py):
        the deposit windows sum to one per particle, so the merged
        field's global sum must equal the deposited mass plus any
        caller-held accumulator, within a compute-dtype budget widened
        for narrow (bf16) mesh storage.  The folds double as NaN/Inf
        tripwires on the mesh-sized accumulator."""
        from .resilience import integrity
        f4 = jnp.float32
        expected = jnp.sum(massa.astype(f4))
        scale = jnp.sum(jnp.abs(massa).astype(f4))
        if prior is not None:
            pw = jnp.asarray(prior).astype(f4)
            expected = expected + jnp.sum(pw)
            scale = scale + jnp.sum(jnp.abs(pw))
        total = float(jnp.sum(block.astype(f4)))
        n = max(int(npart), 1) * int(h) ** 3
        integrity.check_mass('paint.mass', total, float(expected),
                             float(scale), n, self.compute_dtype,
                             self.dtype)

    def _note_dropped(self, count, slack):
        """Observability of an eager mxu bucket overflow, BEFORE the
        backoff retry heals it: the ``paint.dropped`` counter carries
        the would-have-been-lost deposit count across the whole
        process, and an enabled tracer gets a zero-duration
        ``paint.dropped`` event with the count and the slack that
        proved too small — so a post-mortem can see how often the
        ladder climbed and from where."""
        counter('paint.dropped').add(int(count))
        tr = current_tracer()
        if tr is not None:
            tr.event('paint.dropped', {'dropped': int(count),
                                       'slack': float(slack)})

    def _check_overflow_contract(self, capacity, traced, return_dropped):
        if traced and capacity is not None and not return_dropped:
            raise ValueError(
                "paint/readout with an explicit capacity inside jit "
                "cannot retry on exchange overflow; pass "
                "return_dropped=True and check the count after the "
                "step (or use the default capacity, which cannot "
                "overflow)")

    def _retry_grown(self, attempt, block, dropped, capacity, npart):
        """Eager backoff: double the exchange capacity until no
        particle drops (reference: source/mesh/catalog.py:275-315)."""
        cap_max = -(-npart // self.nproc) + 8
        while int(dropped) > 0 and capacity < cap_max:
            capacity = min(2 * capacity, cap_max)
            self.logger.info(
                "exchange overflow (%d dropped); retrying with "
                "capacity=%d" % (int(dropped), capacity))
            block, dropped = attempt(capacity)
        if int(dropped) > 0:
            raise RuntimeError(
                "particle exchange still overflowing at the maximal "
                "capacity %d — this should be impossible" % capacity)
        return block, dropped, capacity

    def readout(self, real, pos, resampler=None, capacity=None,
                return_dropped=False, grad_axis=None):
        """Interpolate a real field at particle positions (inverse of
        paint; reference: pmesh Field.readout, used by FFTRecon at
        algorithms/fftrecon.py:217-268).

        ``capacity``/``return_dropped`` follow the same overflow
        contract as :meth:`paint`; eager calls emit a ``readout`` span
        under diagnostics (same sync semantics as :meth:`paint`).

        ``grad_axis`` (0/1/2) reads the window-DERIVATIVE
        interpolation d(readout)/d(pos[grad_axis]) instead, in CELL
        units (multiply by Nmesh/BoxSize for box units) — the position
        cotangent of the paint adjoint (docs/FORWARD.md).
        """
        if current_tracer() is None or not trace_state_clean():
            return self._readout_impl(real, pos, resampler, capacity,
                                      return_dropped, grad_axis)
        npart = int(pos.shape[0])
        t0 = time.perf_counter()
        with span('readout', npart=npart, nproc=self.nproc,
                  nmesh=int(self.Nmesh[0])):
            res = self._readout_impl(real, pos, resampler, capacity,
                                     return_dropped, grad_axis)
            jax.block_until_ready(res)
        dt = max(time.perf_counter() - t0, 1e-9)
        histogram('readout.mpart_per_s').observe(npart / dt / 1e6)
        return res

    def _readout_impl(self, real, pos, resampler, capacity,
                      return_dropped, grad_axis=None):
        from .utils import is_narrow_float
        real = jnp.asarray(real)
        if is_narrow_float(real.dtype):
            # readout re-widens IMMEDIATELY (the NBK702 contract's
            # read side): interpolation weights and gathers compute
            # f32 — bf16 is a storage format, never an arithmetic one
            real = real.astype(jnp.float32)
        resampler = resampler or _global_options['resampler']
        h = window_support(resampler)
        N0, N1, N2 = self.shape_real
        cpos = self._to_cell_units(pos)
        npart = pos.shape[0]

        if self.nproc == 1:
            out = readout_local(real, cpos, resampler=resampler,
                                period=self.shape_real, origin=0,
                                grad_axis=grad_axis)
            if return_dropped:
                return out, jnp.zeros((), jnp.int32)
            return out

        n0 = self._check_halo(h)
        cell = jnp.mod(jnp.floor(cpos[:, 0]).astype(jnp.int32), N0)
        dest = cell // n0
        gidx = jnp.arange(npart, dtype=jnp.int32)
        traced = isinstance(cpos, jax.core.Tracer)
        self._check_overflow_contract(capacity, traced, return_dropped)
        nproc = self.nproc

        def local(real_l, cpos_l):
            d = jax.lax.axis_index(AXIS)
            origin = d * n0 - h
            ext = halo_fill(real_l, h, nproc)
            return readout_local(ext, cpos_l, resampler=resampler,
                                 period=(N0, N1, N2), origin=origin,
                                 grad_axis=grad_axis)

        def attempt(cap):
            recv, valid, dropped = exchange_by_dest(
                dest, [cpos, gidx], self.comm, cap)
            cpos_r, gidx_r = recv
            vals = jax.shard_map(
                local, mesh=self.comm,
                in_specs=(P(AXIS, None, None), P(AXIS, None)),
                out_specs=P(AXIS))(real, cpos_r)
            # back to original particle order: masked scatter by
            # global index
            vals = jnp.where(valid, vals, 0.0)
            gidx_r = jnp.where(valid, gidx_r, npart)
            out = jnp.zeros((npart + 1,), vals.dtype).at[gidx_r].add(
                vals)
            return out[:npart], dropped

        out, dropped = attempt(capacity)
        if not traced and capacity is not None:
            out, dropped, capacity = self._retry_grown(
                attempt, out, dropped, capacity, npart)
        if return_dropped:
            return out, dropped
        return out

    # -- white noise ------------------------------------------------------

    def generate_whitenoise(self, seed, unitary=False, inverted_phase=False):
        """A hermitian complex field with unit variance per mode, suitable
        for scaling by sqrt(P(k)/V) (reference semantics:
        mockmaker.py:83-134 via pmesh generate_whitenoise).

        Device-count invariant: the draw is a function of (seed, global
        cell index) only.
        """
        key = jax.random.key(seed)
        rdtype = jnp.float32 if self.dtype.itemsize <= 4 else jnp.float64
        g = jax.random.normal(key, self.shape_real, dtype=rdtype)
        if self.comm is not None:
            g = jax.lax.with_sharding_constraint(g, self.sharding())
        eta = self._plan.r2c(g) * (1.0 / np.sqrt(self.Ntot))
        if unitary:
            amp = jnp.abs(eta)
            eta = eta / jnp.where(amp == 0, 1.0, amp)
        if inverted_phase:
            eta = -eta
        return eta

    # -- particle grids ---------------------------------------------------

    def generate_uniform_particle_grid(self, shift=0.5, dtype='f4'):
        """Positions of a uniform lattice of Nmesh^3 particles, offset by
        ``shift`` cells (reference: pm.generate_uniform_particle_grid,
        mockmaker.py:312). Returns (Ntot, 3), x-fastest-varying ordering
        chosen so the particle axis shards along the x slab."""
        N0, N1, N2 = self.shape_real
        H = self.cellsize
        i0 = jnp.arange(N0).reshape(N0, 1, 1)
        i1 = jnp.arange(N1).reshape(1, N1, 1)
        i2 = jnp.arange(N2).reshape(1, 1, N2)
        x = (i0 + shift) * H[0] + 0 * (i1 + i2)
        y = (i1 + shift) * H[1] + 0 * (i0 + i2)
        z = (i2 + shift) * H[2] + 0 * (i0 + i1)
        pos = jnp.stack([x.reshape(-1), y.reshape(-1), z.reshape(-1)],
                        axis=-1).astype(dtype)
        if self.comm is not None:
            pos = shard_leading(self.comm, pos)
        return pos

    def reshape(self, Nmesh):
        """A new ParticleMesh with a different resolution, same box/mesh
        (reference: pm.reshape at base/mesh.py:320, for resampling)."""
        return ParticleMesh(Nmesh, self.BoxSize, self.dtype, self.comm)


def memory_plan(Nmesh, npart, ndevices=1, dtype='f4', resampler='cic',
                paint_method='scatter', paint_chunk=None,
                paint_streams=None, hbm_bytes=16e9, exchange='counted',
                exchange_imbalance=1.5, fft_decomp='slab',
                fft_pencil=None, ingest_chunk_rows=None,
                catalog_bytes=None, workload='fftpower',
                pm_steps=None, nbins=None, bspec_method='fft',
                pairblock_tile=None):
    """Estimated peak per-device HBM for the FFTPower pipeline
    (paint -> rFFT -> |delta_k|^2 -> chunked binning) — the arithmetic
    behind chunk-size choices and the BASELINE.md scale claims
    (Nmesh=1024/1e8 on one v5e chip; Nmesh=2048/1e9 on v5e-16).

    Returns a dict of per-phase byte estimates, ``peak_bytes``, and
    ``fits`` (vs ``hbm_bytes``, 16 GB v5e default, with a 15%
    allocator margin). Estimates, not guarantees — XLA's actual
    buffers vary; the model errs high on the FFT workspace (2x the
    complex field for the out-of-place transposed passes).

    ``exchange`` models the multi-device particle routing buffers:
    'counted' assumes the two-pass counted capacity (eager
    :func:`~nbodykit_tpu.parallel.exchange.counted_capacity` feeding a
    static ~npart/P^2 * ``exchange_imbalance`` per-pair buffer —
    pass 1 of the two-pass exchange); 'ceil' is the traced fallback
    bound ceil(N/P) per pair (npart payload slots per device — the
    safe-but-fat bound that cannot sit next to a 2048^3 mesh).

    ``fft_decomp='pencil'`` (multi-device) swaps the slab FFT
    workspace for the pencil path's staging buffers: exactly
    :data:`~nbodykit_tpu.parallel.dfft.PENCIL_BUFFERS` (= 2) padded
    complex pencil units per device — stage 1's output plus stage 2's
    output, stage 2 donating stage 1's intermediate — where the pad
    grows the Hermitian z length Nc = N2//2+1 to the next multiple of
    Py (``fft_pencil`` = (Px, Py); near-square default).  The report
    gains ``fft_pencil_buffers`` / ``fft_pencil`` keys so the smoke
    gate can assert the documented count at the 1024^3 config.

    ``dtype='bf16'`` prices the half-storage mesh pipeline: the real
    field and the streams-paint replica meshes are billed at 2 bytes
    per cell, while everything that computes — FFT workspace, complex
    field, positions, deposit terms, exchange payloads — stays at the
    f32 compute width (the storage/compute split of docs/PERF.md
    "Halving the bytes").  The report's ``mesh_dtype`` /
    ``mesh_itemsize`` keys record what was priced so admission
    rejections can quote it.

    ``workload='forward'`` prices the differentiable LPT/PM pipeline
    (nbodykit_tpu.forward, docs/FORWARD.md) instead of the FFTPower
    one: ``pm_steps`` kick-drift-kick steps, each a paint -> Poisson
    solve -> 3-component force readout, differentiated end to end
    with ``jax.grad``.  The forward pass adds the particle *state*
    (positions + momenta, 6 compute words per particle) and the three
    per-axis force meshes to the usual mesh pipeline; the REVERSE
    pass is the honest part — ``jax.grad`` holds each step's saved
    primals (the particle state plus two live mesh buffers: painted
    density and potential) across the whole backward sweep, so the
    residual term scales LINEARLY with ``pm_steps`` and the backward
    peak roughly doubles the per-step live mesh working set.  The
    report carries ``forward_state_bytes`` / ``grad_residual_bytes``
    / ``workload`` / ``pm_steps`` so an admission rejection can quote
    exactly which term broke the budget.

    ``workload='bispectrum'`` prices the hybrid higher-order estimator
    (nbodykit_tpu.algorithms.bispectrum, docs/BISPECTRUM.md).  The
    FFT path streams per-shell filtered fields through one compiled
    triple-product program, so its peak holds exactly THREE real
    fields next to the complex spectrum and the transform workspace —
    ``nbins`` shifts the triangle count, not the residency.  The
    direct path (``bspec_method='direct'``) holds no mesh at all: its
    peak is the O(tile^2) dense phase blocks of ops/pairblock
    (``pairblock_tile``; phases + cos/sin images + the weight GEMV,
    billed 4 tile^2 compute words erring high on fusion) plus the
    per-mode accumulators of the ~(4 pi / 3)(nbins+1)^3 lattice modes.
    The report carries ``workload`` / ``nbins`` / ``bspec_method`` and
    the dominant term (``shell_fields_bytes`` or ``pairblock_bytes``)
    so a rejection can quote which estimator broke the budget.

    ``ingest_chunk_rows`` prices the streaming-ingestion pipeline of a
    ``data_ref`` request (nbodykit_tpu.ingest): the resident sharded
    catalog replaces the synthetic ``positions`` term (positions PLUS
    the mass column, 4 compute words per row), and the double-buffered
    H2D staging adds two in-flight padded chunks during the paint
    phase.  ``catalog_bytes`` (total per-DEVICE resident catalog-cache
    bytes, this entry included) overrides the single-entry default so
    admission can price an eviction decision: the cache's
    ``fits(resident)`` predicate is exactly this plan re-asked at a
    candidate residency.
    """
    N = _triplet(Nmesh, 'i8')
    ndev = max(int(ndevices), 1)
    from .utils import mesh_storage_dtype
    sdt = mesh_storage_dtype(dtype)
    item = sdt.itemsize          # STORAGE width: mesh buffers
    citem = max(item, 4)         # COMPUTE width: everything else
    ncells = float(np.prod(N))
    s = window_support(resampler or 'cic')

    real = item * ncells / ndev
    cplx = 2 * citem * (N[0] * N[1] * (N[2] // 2 + 1)) / ndev
    fft_ws = 2 * cplx
    pencil_extra = {}
    if fft_decomp == 'pencil' and ndev > 1:
        from .parallel.dfft import PENCIL_BUFFERS
        if fft_pencil is None:
            from .parallel.runtime import default_pencil_factor
            fft_pencil = default_pencil_factor(ndev)
        px, py = int(fft_pencil[0]), int(fft_pencil[1])
        nc = int(N[2]) // 2 + 1
        ncp = nc + (-nc % py)
        # one padded complex pencil unit per device; the eager path
        # holds PENCIL_BUFFERS of them at peak (stage-1 out + stage-2
        # out, stage 2 donating) — same 2x count as the slab model,
        # scaled by the z pad that makes Nc divisible by Py
        stage = 2 * citem * (N[0] * N[1] * ncp) / ndev
        fft_ws = PENCIL_BUFFERS * stage
        pencil_extra = {'fft_pencil': '%dx%d' % (px, py),
                        'fft_pencil_buffers': PENCIL_BUFFERS,
                        'fft_pencil_pad': float(ncp) / float(nc)}
    pos_b = 3 * citem * npart / ndev
    ingest_extra = {}
    ingest_buf = 0.0
    if ingest_chunk_rows is not None:
        # the resident catalog entry (pos + mass, 4 compute words per
        # row, row-sharded) IS this pipeline's particle storage; a
        # caller-supplied total residency (other cache entries
        # included) replaces the single-entry default
        entry_b = 4 * citem * npart / ndev
        pos_b = float(catalog_bytes) / ndev \
            if catalog_bytes is not None else entry_b
        pos_b = max(pos_b, entry_b)
        # two in-flight padded host chunks (double buffer) staged on
        # device during the streaming paint
        ingest_buf = 2 * 4 * citem * float(ingest_chunk_rows) / ndev
        ingest_extra = {'catalog_bytes': pos_b,
                        'ingest_chunk_buffers': ingest_buf}
    if paint_chunk is None:
        chunk = _global_options['paint_chunk_size']
        if isinstance(chunk, bool) or not isinstance(chunk,
                                                     (int, float)):
            # 'auto' (tune-cache resolution): plan with the effective
            # concrete value
            from .tune.resolve import effective_int_option
            chunk = effective_int_option('paint_chunk_size')
    else:
        chunk = paint_chunk
    live = min(npart / ndev, chunk)
    if paint_method == 'sort':
        # all s^3 deposit terms live at once: (key i32 + val) pairs,
        # doubled by the sort's out-of-place buffers
        paint_tmp = (s ** 3) * (4 + citem) * (npart / ndev) * 2
    elif paint_method == 'segsum':
        # same one-sort streams as 'sort', plus the segment_sum's
        # (n, s^3) totals and gathered run_tot buffers
        paint_tmp = ((s ** 3) * (4 + citem) * (npart / ndev) * 2
                     + 2 * (s ** 3) * citem * (npart / ndev))
    elif paint_method == 'streams':
        # k replica meshes (full mesh units each — THE cost of
        # breaking the scatter chain) next to the live chunk's
        # deposit terms
        if paint_streams is None:
            from .tune.resolve import effective_int_option
            paint_streams = effective_int_option('paint_streams')
        k = max(int(paint_streams), 1)
        # replicas are STORAGE dtype (bf16 halves THE dominant term
        # of this method); the live chunk's deposit terms compute f32
        paint_tmp = k * real + (s ** 3) * (4 + citem) * live
    elif paint_method == 'mxu':
        # padded bucket payload (slack * (pos + mass)), the argsort of
        # the n keys (key + order i32, out-of-place), one x-stripe's
        # W0Y/Z one-hot expansions (transient inside the scan), and the
        # halo-padded mesh rows
        slack = _global_options['paint_bucket_slack']
        nl = npart / ndev
        rb = cb = 8
        rbh, cbh = rb + s - 1, cb + s - 1
        n0l = max(int(N[0]) // ndev, 1)
        ntx = max(-(-n0l // rb), 1)
        # the kernel K-chunks each stripe so the one-hot Z expansion is
        # capped (ops/paint.py ZCHUNK_BYTES); the per-stripe blocks
        # accumulator (nty, M, N2) stays live across all pieces
        from .ops.paint import ZCHUNK_BYTES
        nty = max(-(-int(N[1]) // cb), 1)
        blocks_acc = nty * rbh * cbh * int(N[2]) * citem
        stripe = min(slack * nl / ntx * (rbh * cbh + int(N[2])) * citem,
                     float(ZCHUNK_BYTES) * (1 + rbh * cbh / int(N[2]))
                     ) + blocks_acc
        paint_tmp = (slack * nl * 4 * citem    # padded pos+mass
                     + nl * 8 * 2              # sort keys + order
                     + stripe
                     + (rb + s) * int(N[1]) * int(N[2]) * citem)
    else:
        paint_tmp = (s ** 3) * (4 + citem) * live
    p3 = cplx / 2               # |delta_k|^2 as real of the half-spec
    # multi-device particle routing: send + recv all_to_all buffers,
    # (P, capacity) payload slots each (pos 3*item + mass item + live
    # byte + dest i4). capacity per (src,dst) pair:
    #   counted: ~npart/P^2 * imbalance (two-pass counted exchange)
    #   ceil:    ceil(npart/P)          (traced always-sufficient)
    if ndev > 1:
        payload = 3 * citem + citem + 1 + 4
        if exchange == 'ceil':
            cap = -(-npart // ndev)
        else:
            cap = npart / (ndev * ndev) * exchange_imbalance
        exch = 2 * ndev * cap * payload
    else:
        exch = 0.0
    phases = {
        'real_field': real,
        'complex_field': cplx,
        'fft_workspace': fft_ws,
        'positions': pos_b,
        'paint_temporaries': paint_tmp,
        'exchange_buffers': exch,
        'power3d': p3,
        'mesh_dtype': sdt.name,
        'mesh_itemsize': item,
    }
    phases.update(pencil_extra)
    phases.update(ingest_extra)
    # paint phase: field + positions + temporaries + exchange (+ the
    # in-flight ingest staging chunks on the streaming path);
    # fft phase: real + complex + workspace (positions still resident
    # unless donated); binning adds only O(chunk) slabs
    peak = max(real + pos_b + paint_tmp + exch + ingest_buf,
               real + cplx + fft_ws + pos_b,
               cplx + p3 + pos_b)
    if workload == 'bispectrum':
        nb = max(int(nbins or 4), 1)
        if bspec_method == 'direct':
            # no mesh: dense (tile x tile) phase blocks (phase +
            # cos/sin images + the weight GEMV inputs — 4 tile^2
            # compute words, erring high on what XLA fuses) plus the
            # re/im accumulators over the enumerated lattice modes
            if pairblock_tile is None:
                from .tune.resolve import effective_int_option
                pairblock_tile = effective_int_option('pairblock_tile')
            t = max(int(pairblock_tile), 8)
            nk = 4.0 * np.pi / 3.0 * float(nb + 1) ** 3
            pair_b = 4.0 * t * t * citem
            acc_b = 4.0 * nk * citem
            peak = pos_b + pair_b + acc_b + exch
            phases['pairblock_bytes'] = pair_b
            phases['pairblock_tile'] = t
        else:
            # the streaming Scoccimarro triple product: the complex
            # spectrum stays resident while each triangle's three
            # shell-filtered REAL fields are c2r'd next to the
            # transform workspace — 3 real + 1 complex at peak,
            # independent of nbins (the triangle loop reuses one
            # compiled program)
            shell_b = 3 * real
            peak = max(real + pos_b + paint_tmp + exch + ingest_buf,
                       cplx + shell_b + fft_ws + pos_b)
            phases['shell_fields_bytes'] = shell_b
        phases['workload'] = 'bispectrum'
        phases['nbins'] = nb
        phases['bspec_method'] = bspec_method
    if workload == 'forward':
        steps = max(int(pm_steps or 1), 1)
        # KDK particle state: positions + momenta, always live
        part_state = 6 * citem * npart / ndev
        # per-axis force meshes read out at the particle positions
        force_fields = 3 * real
        fwd_peak = max(real + part_state + paint_tmp + exch,
                       real + cplx + fft_ws + part_state,
                       real + cplx + force_fields + part_state)
        # reverse-mode residuals: jax.grad keeps each step's saved
        # primals (particle state + painted density + potential mesh)
        # alive across the whole backward sweep — linear in pm_steps —
        # and the backward step re-runs a paint/readout pair, doubling
        # that step's live mesh working set on top of the pile
        residual = steps * (part_state + 2 * real)
        peak = fwd_peak + residual + real + cplx
        phases['workload'] = 'forward'
        phases['pm_steps'] = steps
        phases['forward_state_bytes'] = part_state + force_fields
        phases['grad_residual_bytes'] = residual
    phases['peak_bytes'] = peak
    # the budget the admission controller (nbodykit_tpu.serve) prices
    # against: the raw HBM less the 15% allocator margin.  Exposed so
    # structured rejections can quote the numbers they were judged by.
    phases['budget_bytes'] = 0.85 * hbm_bytes
    phases['headroom_bytes'] = 0.85 * hbm_bytes - peak
    phases['fits'] = bool(peak <= 0.85 * hbm_bytes)
    return phases
