"""Compatibility shims across the jax versions the graft toolchain
ships.

The framework is written against the current jax surface
(``jax.shard_map``, the ``jax_num_cpu_devices`` config); some images
bake an older jax (0.4.x) where ``shard_map`` still lives in
``jax.experimental`` and virtual CPU devices are only reachable through
``XLA_FLAGS``.  :func:`apply` runs once at package import (idempotent)
and backfills the modern names, so the rest of the codebase — and the
test suite — uses one spelling everywhere.
"""

import os

import jax


def apply():
    """Backfill modern jax API names onto an older jax. Idempotent."""
    if not hasattr(jax, 'shard_map'):
        import functools

        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map(f, *args, **kwargs):
            # the old experimental shard_map has no replication rule
            # for while_loop (used by the sort paint kernel and the
            # distributed sample sort); modern jax handles it with the
            # check enabled, so disabling the check here is the
            # behavior-preserving translation, not a semantics change
            kwargs.setdefault('check_rep', False)
            return _shard_map(f, *args, **kwargs)

        jax.shard_map = shard_map

        # modern jax defaults to partitionable threefry; the framework's
        # RNG contract (draws are a function of (seed, global index),
        # device-count invariant — rng.py) depends on it for sharded
        # draws, so restore that default on old jax as well
        try:
            if not jax.config.jax_threefry_partitionable:
                jax.config.update('jax_threefry_partitionable', True)
        except AttributeError:  # pragma: no cover - very old jax
            pass

    # the varying-manual-axes (vma) type system does not exist on old
    # jax, so its casts are identities there — and with check_rep=False
    # the shard_map type checker never asks for them
    if not hasattr(jax.lax, 'pvary'):
        jax.lax.pvary = lambda x, axis_name=None: x
    if not hasattr(jax.lax, 'pcast'):
        jax.lax.pcast = lambda x, axis_name=None, to=None: x
    if not hasattr(jax, 'typeof'):
        def _typeof(x):
            from jax import core
            return core.get_aval(x)

        jax.typeof = _typeof


def set_cpu_devices(n):
    """Request ``n`` virtual CPU devices, version-robustly.

    Newer jax exposes the ``jax_num_cpu_devices`` config; older ones
    only honor ``--xla_force_host_platform_device_count`` via
    ``XLA_FLAGS``, which still takes effect when set before the first
    backend initialization (i.e. before the first ``jax.devices()``
    call).  Returns True when the config path worked, False when the
    env-flag fallback was used.
    """
    n = int(n)
    try:
        jax.config.update('jax_num_cpu_devices', n)
        return True
    except AttributeError:
        pass
    flags = os.environ.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=%d' % n
        ).strip()
    return False


apply()
