// Einstein-Boltzmann per-mode integrator (native kernel).
//
// C++ twin of nbodykit_tpu/cosmology/boltzmann.py::BoltzmannSolver.
// The Python BDF path is ~500 us/step of interpreter+scipy overhead;
// a cosmology solve is ~10^6 steps across the k grid, i.e. tens of
// minutes on the single host core.  This kernel runs the same three
// integration phases (zeroth-order tight coupling -> full hierarchy ->
// radiation-streaming + ncdm fluid) with a variable-step BDF2 + Newton
// + dense-LU integrator at ~10 us/step, turning a full-grid solve into
// seconds.  The Python solver remains as the reference implementation;
// tests cross-check the two (see tests/test_boltzmann_native.py).
//
// Everything cosmological is table-driven from Python: background
// lookups arrive as uniform-in-ln(a) arrays, so the physics constants
// and thermodynamics live in exactly one place (boltzmann.py).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 (see cosmology/_native.py).

#include <cmath>
#include <cstring>
#include <vector>
#include <algorithm>
#include <cstdio>

namespace {

struct Tables {
    double gx0, gdx;
    int ng;
    const double *lnHc, *lntau, *lndk, *cs2;
    int ns;                       // ncdm species
    const double *lndrho, *wtab, *cg2tab;   // ns*ng each
    int nq;
    const double *q, *W, *dlnf;
    const double *y0_ncdm;        // m/T0 per species
    int lg, lp, lu, ln;
    double H02_Og, H02_Our, H02_Ob, H02_Oc;  // H0^2 * Omega_i
};

struct Bg {
    double Hc, tau, dk, cs2;
    int i;
    double f;
};

inline Bg lookup(const Tables& T, double x) {
    double t = (x - T.gx0) / T.gdx;
    if (t < 0) t = 0;
    if (t > T.ng - 2) t = T.ng - 2;
    int i = (int)t;
    double f = t - i;
    Bg b;
    b.Hc  = std::exp(T.lnHc[i]  + (T.lnHc[i+1]  - T.lnHc[i])  * f);
    b.tau = std::exp(T.lntau[i] + (T.lntau[i+1] - T.lntau[i]) * f);
    b.dk  = std::exp(T.lndk[i]  + (T.lndk[i+1]  - T.lndk[i])  * f);
    b.cs2 = T.cs2[i] + (T.cs2[i+1] - T.cs2[i]) * f;
    b.i = i; b.f = f;
    return b;
}

inline void lookup_ncdm(const Tables& T, int s, const Bg& b,
                        double* drho, double* w, double* cg2) {
    const double* ld = T.lndrho + (size_t)s * T.ng;
    const double* wt = T.wtab   + (size_t)s * T.ng;
    const double* cg = T.cg2tab + (size_t)s * T.ng;
    *drho = std::exp(ld[b.i] + (ld[b.i+1] - ld[b.i]) * b.f);
    *w    = wt[b.i] + (wt[b.i+1] - wt[b.i]) * b.f;
    *cg2  = cg[b.i] + (cg[b.i+1] - cg[b.i]) * b.f;
}

// ---------------------------------------------------------------------
// right-hand sides; state layouts mirror the Python solver exactly

enum Phase { TCA = 0, FULL = 1, RSA = 2 };

struct Sizes {
    int iFg, iGg, iFu, incdm, nvar;
};

Sizes sizes_for(const Tables& T, Phase ph) {
    Sizes s;
    if (ph == FULL) {
        s.iFg = 5;
        s.iGg = s.iFg + T.lg + 1;
        s.iFu = s.iGg + T.lp + 1;
        s.incdm = s.iFu + T.lu + 1;
        s.nvar = s.incdm + T.ns * T.nq * (T.ln + 1);
    } else if (ph == TCA) {
        s.iFg = -1; s.iGg = -1;
        s.iFu = 6;
        s.incdm = s.iFu + T.lu + 1;
        s.nvar = s.incdm + T.ns * T.nq * (T.ln + 1);
    } else {                          // RSA
        s.iFg = s.iGg = s.iFu = -1;
        s.incdm = 5;
        s.nvar = 5 + 3 * T.ns;
    }
    return s;
}

void rhs(const Tables& T, Phase ph, double k, double x,
         const double* y, double* dy) {
    const Bg b = lookup(T, x);
    const double a = std::exp(x);
    const double Hc = b.Hc, tau = b.tau, dk = b.dk, cs2 = b.cs2;
    const double k2 = k * k;

    const double drg = T.H02_Og / (a * a);
    const double dru = T.H02_Our / (a * a);
    const double drb = T.H02_Ob / a;
    const double drc = T.H02_Oc / a;

    const Sizes S = sizes_for(T, ph);
    const double phi = y[0];
    const double dc = y[1], tc = y[2], db = y[3], tb = y[4];

    double S_sig = 0.0, S_del = 0.0;
    // per-species epsilon cache (nq small)
    double eps[64];

    if (ph == RSA) {
        S_del = drb * db + drc * dc;
        for (int s = 0; s < T.ns; s++) {
            double drn, w, cg2n;
            lookup_ncdm(T, s, b, &drn, &w, &cg2n);
            const double dn = y[5 + 3*s], sn = y[7 + 3*s];
            S_del += drn * dn;
            S_sig += drn * (1.0 + w) * sn;
        }
        const double psi = phi - 4.5 / k2 * S_sig;
        S_del += (drg + dru) * (-4.0 * psi);
        const double phidot = -Hc * psi - k2 / (3.0 * Hc) * phi
                              - S_del / (2.0 * Hc);
        dy[0] = phidot;
        dy[1] = -tc + 3.0 * phidot;
        dy[2] = -Hc * tc + k2 * psi;
        dy[3] = -tb + 3.0 * phidot;
        dy[4] = -Hc * tb + cs2 * k2 * db + k2 * psi
                + (4.0 * drg) / (3.0 * drb) * dk * (0.0 - tb);
        for (int s = 0; s < T.ns; s++) {
            double drn, w, cg2n;
            lookup_ncdm(T, s, b, &drn, &w, &cg2n);
            const double dn = y[5+3*s], tn = y[6+3*s], sn = y[7+3*s];
            dy[5+3*s] = -(1.0 + w) * (tn - 3.0 * phidot)
                        - 3.0 * Hc * (cg2n - w) * dn;
            dy[6+3*s] = -Hc * (1.0 - 3.0 * cg2n) * tn
                        + cg2n / (1.0 + w) * k2 * dn - k2 * sn
                        + k2 * psi;
            const double cvis2 = 3.0 * w * cg2n;
            dy[7+3*s] = -3.0 * Hc * sn
                        + (8.0/3.0) * cvis2 / (1.0 + w) * tn;
        }
        const double invHc = 1.0 / Hc;
        for (int i = 0; i < S.nvar; i++) dy[i] *= invHc;
        return;
    }

    // shared: ncdm hierarchy moments (TCA and FULL)
    const int nP = T.ln + 1;
    for (int s = 0; s < T.ns; s++) {
        const double ya = a * T.y0_ncdm[s];
        const double* P = y + S.incdm + s * T.nq * nP;
        double norm = 0.0, d0 = 0.0, s2 = 0.0;
        for (int j = 0; j < T.nq; j++) {
            const double e = std::sqrt(T.q[j]*T.q[j] + ya*ya);
            eps[s*T.nq + j] = e;
            const double We = T.W[j] * e;
            norm += We;
            d0 += We * P[j*nP + 0];
            s2 += T.W[j] * T.q[j]*T.q[j] / e * P[j*nP + 2];
        }
        double drn, w, cg2n;
        lookup_ncdm(T, s, b, &drn, &w, &cg2n);
        S_del += drn * d0 / norm;
        S_sig += drn * (2.0/3.0) * s2 / norm;
    }

    double psi, phidot;
    if (ph == FULL) {
        const double* Fg = y + S.iFg;
        const double* Gg = y + S.iGg;
        const double* Fu = y + S.iFu;
        S_sig += (2.0/3.0) * (drg * Fg[2] + dru * Fu[2]);
        psi = phi - 4.5 / k2 * S_sig;
        S_del += drg * Fg[0] + dru * Fu[0] + drb * db + drc * dc;
        phidot = -Hc * psi - k2 / (3.0 * Hc) * phi - S_del / (2.0 * Hc);

        dy[0] = phidot;
        dy[1] = -tc + 3.0 * phidot;
        dy[2] = -Hc * tc + k2 * psi;
        const double thg = 0.75 * k * Fg[1];
        dy[3] = -tb + 3.0 * phidot;
        dy[4] = -Hc * tb + cs2 * k2 * db + k2 * psi
                + (4.0 * drg) / (3.0 * drb) * dk * (thg - tb);

        double* dFg = dy + S.iFg;
        dFg[0] = -k * Fg[1] + 4.0 * phidot;
        dFg[1] = (k/3.0) * (Fg[0] - 2.0*Fg[2]) + (4.0*k/3.0) * psi
                 + dk * (4.0 * tb / (3.0 * k) - Fg[1]);
        dFg[2] = (k/5.0) * (2.0*Fg[1] - 3.0*Fg[3])
                 - dk * (0.9*Fg[2] - 0.1*(Gg[0] + Gg[2]));
        for (int l = 3; l < T.lg; l++)
            dFg[l] = k / (2.0*l + 1.0)
                     * (l * Fg[l-1] - (l+1.0) * Fg[l+1]) - dk * Fg[l];
        dFg[T.lg] = k * Fg[T.lg-1]
                    - ((T.lg + 1.0) / tau + dk) * Fg[T.lg];

        double* dGg = dy + S.iGg;
        const double src = 0.5 * (Fg[2] + Gg[0] + Gg[2]);
        dGg[0] = -k * Gg[1] + dk * (-Gg[0] + src);
        for (int l = 1; l < T.lp; l++)
            dGg[l] = k / (2.0*l + 1.0)
                     * (l * Gg[l-1] - (l+1.0) * Gg[l+1]) - dk * Gg[l];
        dGg[2] += dk * src / 5.0;
        dGg[T.lp] = k * Gg[T.lp-1]
                    - ((T.lp + 1.0) / tau + dk) * Gg[T.lp];

        double* dFu = dy + S.iFu;
        dFu[0] = -k * Fu[1] + 4.0 * phidot;
        dFu[1] = (k/3.0) * (Fu[0] - 2.0*Fu[2]) + (4.0*k/3.0) * psi;
        for (int l = 2; l < T.lu; l++)
            dFu[l] = k / (2.0*l + 1.0)
                     * (l * Fu[l-1] - (l+1.0) * Fu[l+1]);
        dFu[T.lu] = k * Fu[T.lu-1] - ((T.lu + 1.0) / tau) * Fu[T.lu];
    } else {                     // TCA
        const double tgb = y[4], dg = y[5];
        const double* Fu = y + S.iFu;
        S_sig += (2.0/3.0) * dru * Fu[2];
        psi = phi - 4.5 / k2 * S_sig;
        S_del += drg * dg + dru * Fu[0] + drb * db + drc * dc;
        phidot = -Hc * psi - k2 / (3.0 * Hc) * phi - S_del / (2.0 * Hc);

        const double R = (4.0 * drg) / (3.0 * drb);
        dy[0] = phidot;
        dy[1] = -tc + 3.0 * phidot;
        dy[2] = -Hc * tc + k2 * psi;
        dy[3] = -tgb + 3.0 * phidot;
        dy[4] = (-Hc * tgb + cs2 * k2 * db + R * k2 * dg / 4.0)
                / (1.0 + R) + k2 * psi;
        dy[5] = -(4.0/3.0) * tgb + 4.0 * phidot;

        double* dFu = dy + S.iFu;
        dFu[0] = -k * Fu[1] + 4.0 * phidot;
        dFu[1] = (k/3.0) * (Fu[0] - 2.0*Fu[2]) + (4.0*k/3.0) * psi;
        for (int l = 2; l < T.lu; l++)
            dFu[l] = k / (2.0*l + 1.0)
                     * (l * Fu[l-1] - (l+1.0) * Fu[l+1]);
        dFu[T.lu] = k * Fu[T.lu-1] - ((T.lu + 1.0) / tau) * Fu[T.lu];
    }

    // ncdm hierarchies (TCA and FULL share the form)
    for (int s = 0; s < T.ns; s++) {
        const double* P = y + S.incdm + s * T.nq * nP;
        double* dP = dy + S.incdm + s * T.nq * nP;
        for (int j = 0; j < T.nq; j++) {
            const double e = eps[s*T.nq + j];
            const double qk_e = T.q[j] * k / e;
            const double dl = T.dlnf[j];
            const double* Pj = P + j * nP;
            double* dPj = dP + j * nP;
            dPj[0] = -qk_e * Pj[1] - phidot * dl;
            dPj[1] = qk_e / 3.0 * (Pj[0] - 2.0 * Pj[2])
                     - (e * k / (3.0 * T.q[j])) * psi * dl;
            for (int l = 2; l < T.ln; l++)
                dPj[l] = qk_e / (2.0*l + 1.0)
                         * (l * Pj[l-1] - (l+1.0) * Pj[l+1]);
            dPj[T.ln] = qk_e * Pj[T.ln-1]
                        - ((T.ln + 1.0) / tau) * Pj[T.ln];
        }
    }

    const double invHc = 1.0 / Hc;
    for (int i = 0; i < S.nvar; i++) dy[i] *= invHc;
}

// ---------------------------------------------------------------------
// dense LU with partial pivoting

struct LU {
    std::vector<double> A;
    std::vector<int> piv;
    int n = 0;

    bool factor(const double* M, int n_) {
        n = n_;
        A.assign(M, M + (size_t)n * n);
        piv.resize(n);
        for (int c = 0; c < n; c++) {
            int p = c;
            double mx = std::fabs(A[(size_t)c*n + c]);
            for (int r = c + 1; r < n; r++) {
                double v = std::fabs(A[(size_t)r*n + c]);
                if (v > mx) { mx = v; p = r; }
            }
            if (mx == 0.0) return false;
            piv[c] = p;
            if (p != c)
                for (int j = 0; j < n; j++)
                    std::swap(A[(size_t)c*n + j], A[(size_t)p*n + j]);
            const double inv = 1.0 / A[(size_t)c*n + c];
            for (int r = c + 1; r < n; r++) {
                const double f = A[(size_t)r*n + c] * inv;
                A[(size_t)r*n + c] = f;
                if (f != 0.0)
                    for (int j = c + 1; j < n; j++)
                        A[(size_t)r*n + j] -= f * A[(size_t)c*n + j];
            }
        }
        return true;
    }

    void solve(double* x) const {
        for (int c = 0; c < n; c++) {
            if (piv[c] != c) std::swap(x[c], x[piv[c]]);
            for (int r = c + 1; r < n; r++)
                x[r] -= A[(size_t)r*n + c] * x[c];
        }
        for (int c = n - 1; c >= 0; c--) {
            x[c] /= A[(size_t)c*n + c];
            for (int r = 0; r < c; r++)
                x[r] -= A[(size_t)r*n + c] * x[c];
        }
    }
};

// ---------------------------------------------------------------------
// variable-step BDF2 integrator with Newton iterations
//
// BDF2 (variable step, rho = h_n / h_{n-1}):
//   y_{n+1} - beta h f(y_{n+1}) = alpha1 y_n + alpha2 y_{n-1}
//   alpha1 = (1+rho)^2/(1+2rho), alpha2 = -rho^2/(1+2rho),
//   beta = (1+rho)/(1+2rho)
// First step: implicit Euler.  Error estimate: corrector minus the
// quadratic predictor through (y_{n-1}, y_n, f_n).

struct Integrator {
    const Tables& T;
    Phase ph;
    double k;
    int n;
    double rtol, atol_phi, atol;
    std::vector<double> J, M, yprev, ycur, f0, fwork, ywork, dy, pred;
    LU lu;
    double lu_gamma = -1.0;
    int steps_since_jac = 0;
    long nsteps = 0, nfev = 0;

    Integrator(const Tables& T_, Phase ph_, double k_, int n_,
               double rtol_)
        : T(T_), ph(ph_), k(k_), n(n_), rtol(rtol_),
          atol_phi(1e-11), atol(1e-9) {
        J.resize((size_t)n * n);
        M.resize((size_t)n * n);
        yprev.resize(n); ycur.resize(n); f0.resize(n);
        fwork.resize(n); ywork.resize(n); dy.resize(n); pred.resize(n);
    }

    void eval(double x, const double* y, double* out) {
        rhs(T, ph, k, x, y, out);
        nfev++;
    }

    void jacobian(double x, const double* y, const double* f) {
        // forward-difference columns
        std::memcpy(ywork.data(), y, n * sizeof(double));
        for (int j = 0; j < n; j++) {
            const double yj = y[j];
            const double h = 1e-8 * std::max(std::fabs(yj), 1e-5);
            ywork[j] = yj + h;
            eval(x, ywork.data(), fwork.data());
            const double inv = 1.0 / h;
            for (int i = 0; i < n; i++)
                J[(size_t)i*n + j] = (fwork[i] - f[i]) * inv;
            ywork[j] = yj;
        }
        steps_since_jac = 0;
    }

    bool build_lu(double gamma) {
        for (int i = 0; i < n; i++)
            for (int j = 0; j < n; j++)
                M[(size_t)i*n + j] = (i == j ? 1.0 : 0.0)
                                     - gamma * J[(size_t)i*n + j];
        lu_gamma = gamma;
        return lu.factor(M.data(), n);
    }

    double err_norm(const double* e, const double* y) const {
        double s = 0.0;
        for (int i = 0; i < n; i++) {
            const double sc = (i == 0 ? atol_phi : atol)
                              + rtol * std::fabs(y[i]);
            const double r = e[i] / sc;
            s += r * r;
        }
        return std::sqrt(s / n);
    }

    // Newton solve of  y - gamma f(x1, y) = rhs_vec ; y starts at pred
    bool newton(double x1, double gamma, const double* rhs_vec,
                double* y, double x_jac, const double* y_jac) {
        for (int attempt = 0; attempt < 2; attempt++) {
            bool ok = false;
            double last = 1e300;
            std::memcpy(ycur.data(), y, n * sizeof(double));
            for (int it = 0; it < 6; it++) {
                eval(x1, ycur.data(), fwork.data());
                for (int i = 0; i < n; i++)
                    dy[i] = rhs_vec[i] + gamma * fwork[i] - ycur[i];
                lu.solve(dy.data());
                double nrm = err_norm(dy.data(), ycur.data());
                for (int i = 0; i < n; i++) ycur[i] += dy[i];
                if (nrm < 0.03) { ok = true; break; }
                if (it > 1 && nrm > 0.9 * last) break;  // not converging
                last = nrm;
            }
            if (ok) {
                std::memcpy(y, ycur.data(), n * sizeof(double));
                return true;
            }
            // refresh Jacobian at the step base and retry once
            eval(x_jac, y_jac, f0.data());
            jacobian(x_jac, y_jac, f0.data());
            if (!build_lu(gamma)) return false;
        }
        return false;
    }

    // integrate y from x0 to x1; y updated in place.
    bool run(double x0, double x1, double* y) {
        if (x1 <= x0 + 1e-14) return true;
        double x = x0;
        double h = std::min(1e-4, (x1 - x0) * 0.1);
        double hprev = -1.0;
        bool have_prev = false;

        eval(x, y, f0.data());
        jacobian(x, y, f0.data());

        int consecutive_fail = 0;
        while (x < x1 - 1e-13) {
            if (x + h > x1) h = x1 - x;
            const double rho = have_prev ? h / hprev : 0.0;
            double a1, a2, beta;
            if (!have_prev) {              // implicit Euler
                a1 = 1.0; a2 = 0.0; beta = 1.0;
            } else {
                a1 = (1.0 + rho) * (1.0 + rho) / (1.0 + 2.0 * rho);
                a2 = -rho * rho / (1.0 + 2.0 * rho);
                beta = (1.0 + rho) / (1.0 + 2.0 * rho);
            }
            const double gamma = beta * h;
            if (lu_gamma < 0
                || std::fabs(gamma - lu_gamma) > 0.2 * lu_gamma
                || steps_since_jac > 50) {
                if (steps_since_jac > 50) {
                    eval(x, y, f0.data());
                    jacobian(x, y, f0.data());
                }
                if (!build_lu(gamma)) return false;
            }

            // predictor: quadratic through (y_{n-1}, y_n, f_n), so the
            // corrector-predictor gap measures the genuine O(h^3) BDF2
            // local error (a first-order predictor is blind to the
            // slowly-growing parasitic mode of variable-step BDF2)
            eval(x, y, f0.data());
            if (!have_prev) {
                for (int i = 0; i < n; i++)
                    pred[i] = y[i] + h * f0[i];
            } else {
                const double inv_hp = 1.0 / hprev;
                for (int i = 0; i < n; i++) {
                    const double slope_hist = (y[i] - yprev[i]) * inv_hp;
                    const double ydd = 2.0 * (f0[i] - slope_hist)
                                       * inv_hp;
                    pred[i] = y[i] + h * f0[i] + 0.5 * h * h * ydd;
                }
            }

            for (int i = 0; i < n; i++)
                ywork[i] = a1 * y[i] + a2 * yprev[i];
            std::vector<double> ynew(pred);
            if (!newton(x + h, gamma, ywork.data(), ynew.data(), x, y)) {
                h *= 0.25;
                lu_gamma = -1.0;
                if (++consecutive_fail > 40) return false;
                continue;
            }

            // error estimate: corrector vs predictor
            for (int i = 0; i < n; i++)
                dy[i] = (ynew[i] - pred[i]) / 3.0;
            const double err = err_norm(dy.data(), ynew.data());
            if (err > 1.0 && h > 1e-10) {
                h *= std::max(0.2, 0.9 * std::pow(err, -1.0/3.0));
                if (++consecutive_fail > 40) return false;
                continue;
            }
            consecutive_fail = 0;

            std::memcpy(yprev.data(), y, n * sizeof(double));
            std::memcpy(y, ynew.data(), n * sizeof(double));
            hprev = h;
            have_prev = true;
            x += h;
            nsteps++;
            steps_since_jac++;
            if (nsteps > 4000000) return false;
            // variable-step BDF2 is zero-stable only for step ratios
            // rho <= 1+sqrt(2); cap growth safely below that
            const double fac = (err > 1e-12)
                ? std::min(2.0, 0.9 * std::pow(err, -1.0/3.0)) : 2.0;
            h = std::min(h * fac, (x1 - x0));
            h = std::min(h, 0.12);       // at most ~1/8 e-fold per step
            if (h <= 0) h = 1e-12;
        }
        return true;
    }

    static double rho2_extrap_unused(double yn, double ynm1, double rho,
                              double h, double hprev, double fn) {
        // quadratic-ish predictor: linear through (y_{n-1}, y_n)
        // blended with the derivative
        (void)hprev; (void)rho;
        const double slope_hist = (yn - ynm1);
        (void)slope_hist;
        return h * fn;
    }
};

}  // namespace

// ---------------------------------------------------------------------
// C ABI

extern "C" {

// record layout matches the Python solver's output dict order
// [phi, psi, d_cdm, t_cdm, d_b, t_b, d_g, t_g, d_ur, t_ur,
//  d_ncdm, t_ncdm]
int nbk_solve_mode(
    double gx0, double gdx, int ng,
    const double* lnHc, const double* lntau, const double* lndk,
    const double* cs2tab,
    int ns, const double* lndrho, const double* wtab,
    const double* cg2tab,
    int nq, const double* q, const double* W, const double* dlnf,
    const double* y0_ncdm,
    int lg, int lp, int lu, int ln,
    double H02_Og, double H02_Our, double H02_Ob, double H02_Oc,
    double k, double lna0, double x_tc, double x_sw,
    const double* y_init_full, int nvar_full,
    double rtol,
    int nout, const double* lna_out,
    double* out, long* stats)
{
    Tables T;
    T.gx0 = gx0; T.gdx = gdx; T.ng = ng;
    T.lnHc = lnHc; T.lntau = lntau; T.lndk = lndk; T.cs2 = cs2tab;
    T.ns = ns; T.lndrho = lndrho; T.wtab = wtab; T.cg2tab = cg2tab;
    T.nq = nq; T.q = q; T.W = W; T.dlnf = dlnf; T.y0_ncdm = y0_ncdm;
    T.lg = lg; T.lp = lp; T.lu = lu; T.ln = ln;
    T.H02_Og = H02_Og; T.H02_Our = H02_Our;
    T.H02_Ob = H02_Ob; T.H02_Oc = H02_Oc;
    if (nq > 64) return -10;

    const Sizes Sf = sizes_for(T, FULL);
    const Sizes St = sizes_for(T, TCA);
    const Sizes Sr = sizes_for(T, RSA);
    if (Sf.nvar != nvar_full) return -11;

    const int nP = T.ln + 1;
    const int n_ur_ncdm = (T.lu + 1) + T.ns * T.nq * nP;

    // --- record helper (from a FULL-layout state) ---------------------
    auto record_full = [&](double x, const double* y, double* rec) {
        const Bg b = lookup(T, x);
        const double a = std::exp(x);
        const double drg = T.H02_Og / (a * a);
        const double dru = T.H02_Our / (a * a);
        const double* Fg = y + Sf.iFg;
        const double* Fu = y + Sf.iFu;
        rec[0] = y[0];
        rec[2] = y[1]; rec[3] = y[2]; rec[4] = y[3]; rec[5] = y[4];
        rec[6] = Fg[0]; rec[7] = 0.75 * k * Fg[1];
        rec[8] = Fu[0]; rec[9] = 0.75 * k * Fu[1];
        double S_sig = (2.0/3.0) * (drg * Fg[2] + dru * Fu[2]);
        double dtot = 0.0, ttot = 0.0, wsum = 0.0;
        for (int s = 0; s < T.ns; s++) {
            const double ya = a * T.y0_ncdm[s];
            const double* P = y + Sf.incdm + s * T.nq * nP;
            double norm = 0.0, d0 = 0.0, t1 = 0.0, s2 = 0.0;
            for (int j = 0; j < T.nq; j++) {
                const double e = std::sqrt(T.q[j]*T.q[j] + ya*ya);
                const double We = T.W[j] * e;
                norm += We;
                d0 += We * P[j*nP];
                t1 += T.W[j] * T.q[j] * P[j*nP + 1];
                s2 += T.W[j] * T.q[j]*T.q[j] / e * P[j*nP + 2];
            }
            double drn, w, cg2n;
            lookup_ncdm(T, s, b, &drn, &w, &cg2n);
            dtot += drn * d0 / norm;
            ttot += drn * k * t1 / norm / (1.0 + w);
            wsum += drn;
            S_sig += drn * (2.0/3.0) * s2 / norm;
        }
        rec[10] = wsum > 0 ? dtot / wsum : 0.0;
        rec[11] = wsum > 0 ? ttot / wsum : 0.0;
        rec[1] = y[0] - 4.5 / (k * k) * S_sig;
    };

    auto record_rsa = [&](double x, const double* y, double* rec) {
        const Bg b = lookup(T, x);
        rec[0] = y[0];
        rec[2] = y[1]; rec[3] = y[2]; rec[4] = y[3]; rec[5] = y[4];
        double S_sig = 0.0, dtot = 0.0, ttot = 0.0, wsum = 0.0;
        for (int s = 0; s < T.ns; s++) {
            double drn, w, cg2n;
            lookup_ncdm(T, s, b, &drn, &w, &cg2n);
            S_sig += drn * (1.0 + w) * y[7 + 3*s];
            dtot += drn * y[5 + 3*s];
            ttot += drn * y[6 + 3*s];
            wsum += drn;
        }
        const double psi = y[0] - 4.5 / (k * k) * S_sig;
        rec[1] = psi;
        rec[6] = -4.0 * psi; rec[7] = 0.0;
        rec[8] = -4.0 * psi; rec[9] = 0.0;
        rec[10] = wsum > 0 ? dtot / wsum : 0.0;
        rec[11] = wsum > 0 ? ttot / wsum : 0.0;
    };

    // --- initial TCA state from the provided full-layout ICs ----------
    std::vector<double> y(St.nvar, 0.0);
    y[0] = y_init_full[0];
    for (int i = 1; i < 5; i++) y[i] = y_init_full[i];
    y[5] = y_init_full[Sf.iFg];
    std::memcpy(y.data() + 6, y_init_full + Sf.iFu,
                n_ur_ncdm * sizeof(double));

    long total_steps = 0, total_fev = 0;
    int iout = 0;

    // --- phase 0: TCA --------------------------------------------------
    {
        Integrator I(T, TCA, k, St.nvar, rtol);
        double x = lna0;
        while (iout < nout && lna_out[iout] < x_tc) {
            if (!I.run(x, lna_out[iout], y.data())) return -1;
            x = lna_out[iout];
            // map to full for recording
            std::vector<double> yf(Sf.nvar, 0.0);
            yf[0] = y[0];
            for (int i = 1; i < 5; i++) yf[i] = y[i];
            const Bg b = lookup(T, x);
            yf[Sf.iFg] = y[5];
            yf[Sf.iFg + 1] = 4.0 * y[4] / (3.0 * k);
            yf[Sf.iFg + 2] = (32.0/45.0) * y[4] / b.dk;
            std::memcpy(yf.data() + Sf.iFu, y.data() + 6,
                        n_ur_ncdm * sizeof(double));
            record_full(x, yf.data(), out + (size_t)iout * 12);
            iout++;
        }
        if (!I.run(x, x_tc, y.data())) return -1;
        total_steps += I.nsteps; total_fev += I.nfev;
    }

    // --- map TCA -> FULL ----------------------------------------------
    std::vector<double> yf(Sf.nvar, 0.0);
    {
        const Bg b = lookup(T, x_tc);
        yf[0] = y[0];
        for (int i = 1; i < 5; i++) yf[i] = y[i];
        yf[Sf.iFg] = y[5];
        yf[Sf.iFg + 1] = 4.0 * y[4] / (3.0 * k);
        yf[Sf.iFg + 2] = (32.0/45.0) * y[4] / b.dk;
        std::memcpy(yf.data() + Sf.iFu, y.data() + 6,
                    n_ur_ncdm * sizeof(double));
    }

    // --- phase 1: FULL -------------------------------------------------
    const bool has_rsa = (x_sw < 0.0) && (x_sw > x_tc);
    const double x_end1 = has_rsa ? x_sw : 0.0;
    {
        Integrator I(T, FULL, k, Sf.nvar, rtol);
        double x = x_tc;
        while (iout < nout && lna_out[iout] < x_end1) {
            if (!I.run(x, lna_out[iout], yf.data())) return -2;
            x = lna_out[iout];
            record_full(x, yf.data(), out + (size_t)iout * 12);
            iout++;
        }
        if (!I.run(x, x_end1, yf.data())) return -2;
        total_steps += I.nsteps; total_fev += I.nfev;
    }
    if (!has_rsa) {
        // record any boundary outputs at exactly 0.0
        while (iout < nout) {
            record_full(0.0, yf.data(), out + (size_t)iout * 12);
            iout++;
        }
        if (stats) { stats[0] = total_steps; stats[1] = total_fev; }
        return 0;
    }

    // --- map FULL -> RSA ----------------------------------------------
    std::vector<double> yr(Sr.nvar, 0.0);
    {
        const double a_sw = std::exp(x_sw);
        const Bg b = lookup(T, x_sw);
        for (int i = 0; i < 5; i++) yr[i] = yf[i];
        for (int s = 0; s < T.ns; s++) {
            const double ya = a_sw * T.y0_ncdm[s];
            const double* P = yf.data() + Sf.incdm + s * T.nq * nP;
            double norm = 0.0, d0 = 0.0, t1 = 0.0, s2 = 0.0;
            for (int j = 0; j < T.nq; j++) {
                const double e = std::sqrt(T.q[j]*T.q[j] + ya*ya);
                const double We = T.W[j] * e;
                norm += We;
                d0 += We * P[j*nP];
                t1 += T.W[j] * T.q[j] * P[j*nP + 1];
                s2 += T.W[j] * T.q[j]*T.q[j] / e * P[j*nP + 2];
            }
            double drn, w, cg2n;
            lookup_ncdm(T, s, b, &drn, &w, &cg2n);
            yr[5 + 3*s] = d0 / norm;
            yr[6 + 3*s] = k * t1 / norm / (1.0 + w);
            yr[7 + 3*s] = (2.0/3.0) * s2 / norm / (1.0 + w);
        }
    }

    // --- phase 2: RSA --------------------------------------------------
    {
        Integrator I(T, RSA, k, Sr.nvar, rtol);
        double x = x_sw;
        while (iout < nout) {
            const double xt = std::min(lna_out[iout], 0.0);
            if (!I.run(x, xt, yr.data())) return -3;
            x = xt;
            record_rsa(x, yr.data(), out + (size_t)iout * 12);
            iout++;
        }
        total_steps += I.nsteps; total_fev += I.nfev;
    }
    if (stats) { stats[0] = total_steps; stats[1] = total_fev; }
    return 0;
}

}  // extern "C"
