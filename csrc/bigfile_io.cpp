// Native bigfile block IO: parallel part-file reads + checksum.
//
// The reference consumes the bigfile format through the C library
// (reference nbodykit/io/bigfile.py:16); here the format codec is
// nbodykit_tpu/io/bigfile.py (pure numpy) and this kernel is the
// data-loader fast path: one reader thread per part-file segment
// (catalog columns are striped over NFILE hex-named files), plus the
// format's 32-bit byte-sum checksum. Bound via ctypes (plain C ABI —
// pybind11 is not available in this environment).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread bigfile_io.cpp

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Segment {
    char path[4096];
    long file_offset;   // bytes into the part file
    long out_offset;    // bytes into the output buffer
    long nbytes;
};

int read_segment(const Segment& seg, unsigned char* out) {
    FILE* f = std::fopen(seg.path, "rb");
    if (!f) return -1;
    if (std::fseek(f, seg.file_offset, SEEK_SET) != 0) {
        std::fclose(f);
        return -1;
    }
    size_t got = std::fread(out + seg.out_offset, 1,
                            (size_t)seg.nbytes, f);
    std::fclose(f);
    return got == (size_t)seg.nbytes ? 0 : -1;
}

}  // namespace

extern "C" {

// 32-bit byte-sum checksum over a buffer (the bigfile on-disk
// convention: unsigned 32-bit wraparound sum of all payload bytes).
unsigned int nbk_checksum(const unsigned char* buf, long n) {
    // 64-bit partial sums let the compiler vectorize; fold at the end
    uint64_t acc = 0;
    long i = 0;
    for (; i + 8 <= n; i += 8) {
        acc += buf[i] + buf[i + 1] + buf[i + 2] + buf[i + 3]
             + buf[i + 4] + buf[i + 5] + buf[i + 6] + buf[i + 7];
    }
    for (; i < n; ++i) acc += buf[i];
    return (unsigned int)(acc & 0xffffffffu);
}

// Read records [start, stop) of a block striped over `nfile` part
// files under `dir` (files named %06X, record bounds[i]..bounds[i+1]
// in file i). `itemsize` is bytes per record. Segments are read by up
// to `nthreads` concurrent readers. Returns 0 on success, -1 on any
// open/seek/short-read failure.
int nbk_bigfile_read(const char* dir, int nfile, const long* bounds,
                     long itemsize, long start, long stop,
                     unsigned char* out, int nthreads) {
    std::vector<Segment> segs;
    for (int i = 0; i < nfile; ++i) {
        long lo = bounds[i], hi = bounds[i + 1];
        long s = start > lo ? start : lo;
        long e = stop < hi ? stop : hi;
        if (s >= e) continue;
        Segment seg;
        std::snprintf(seg.path, sizeof(seg.path), "%s/%06X", dir, i);
        seg.file_offset = (s - lo) * itemsize;
        seg.out_offset = (s - start) * itemsize;
        seg.nbytes = (e - s) * itemsize;
        segs.push_back(seg);
    }
    if (segs.empty()) return 0;
    if (nthreads < 1) nthreads = 1;
    if ((size_t)nthreads > segs.size()) nthreads = (int)segs.size();

    std::atomic<size_t> next(0);
    std::atomic<int> err(0);
    auto worker = [&]() {
        for (;;) {
            size_t j = next.fetch_add(1);
            if (j >= segs.size() || err.load()) break;
            if (read_segment(segs[j], out) != 0) err.store(-1);
        }
    };
    if (nthreads == 1) {
        worker();
    } else {
        std::vector<std::thread> pool;
        pool.reserve(nthreads);
        for (int t = 0; t < nthreads; ++t) pool.emplace_back(worker);
        for (auto& th : pool) th.join();
    }
    return err.load();
}

}  // extern "C"
