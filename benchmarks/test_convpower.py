"""ConvolvedFFTPower benchmark (reference
benchmarks/test_convpower.py:7-25): FKP catalog with 10x randoms,
poles [0, 2, 4], dk=0.005."""

import numpy as np


def test_convpower(sample, benchmark):
    from nbodykit_tpu.lab import UniformCatalog
    from nbodykit_tpu.algorithms.convpower import (FKPCatalog,
                                                   ConvolvedFFTPower)

    nbar = sample['N'] / sample['BoxSize'] ** 3
    with benchmark('Data'):
        data = UniformCatalog(nbar=nbar, BoxSize=sample['BoxSize'],
                              seed=42)
        randoms = UniformCatalog(nbar=10 * nbar,
                                 BoxSize=sample['BoxSize'], seed=84)
        data['NZ'] = nbar * np.ones(data.size)
        randoms['NZ'] = nbar * np.ones(randoms.size)
        fkp = FKPCatalog(data, randoms)
        mesh = fkp.to_mesh(Nmesh=sample['Nmesh'], resampler='tsc')

    with benchmark('Algorithm'):
        r = ConvolvedFFTPower(mesh, poles=[0, 2, 4], dk=0.005)
        assert np.isfinite(
            np.asarray(r.poles['power_0'].real)).any()
