"""FOF benchmark (reference benchmarks/test_fof.py:7-26):
linking_length=0.2, nmin=20, then find_features + to_halos."""

import numpy as np


def test_fof(sample, benchmark):
    from nbodykit_tpu.lab import LogNormalCatalog, LinearPower, FOF
    from nbodykit_tpu.cosmology import Planck15

    with benchmark('Data'):
        Plin = LinearPower(Planck15, redshift=0.55,
                           transfer='EisensteinHu')
        nbar = sample['N'] / sample['BoxSize'] ** 3
        cat = LogNormalCatalog(Plin=Plin, nbar=nbar,
                               BoxSize=sample['BoxSize'],
                               Nmesh=sample['Nmesh'], bias=2.0, seed=42)

    with benchmark('Algorithm'):
        fof = FOF(cat, linking_length=0.2, nmin=20)
        halos = fof.to_halos(1e12, Planck15, 0.0)
        assert len(np.asarray(halos['Position'])) >= 0
