"""Benchmark suite configuration.

Mirrors the reference's ``benchmarks/`` pytest harness
(``/root/reference/benchmarks/conftest.py:25-29``): a session fixture
defines the sample scales and a ``benchmark(name)`` context manager
times labelled phases, appending one JSON record per test to
``BENCH_DIR`` (env, default ``./.bench_results``).

Scale is chosen with ``--bench-scale`` (default ``test`` so the suite
is cheap enough for CPU CI; ``boss_like``/``desi_like``/``dm_like``
are the reference's production scales for TPU runs).
"""

import contextlib
import json
import os
import time

import pytest

# (BoxSize, Nmesh, N) — the reference's sample definitions
SCALES = {
    'test': dict(BoxSize=100.0, Nmesh=64, N=1000),
    'boss_like': dict(BoxSize=2500.0, Nmesh=1024, N=int(1e6)),
    'desi_like': dict(BoxSize=5000.0, Nmesh=1024, N=int(1e7)),
    'dm_like': dict(BoxSize=5000.0, Nmesh=1024, N=512 ** 3),
}


def pytest_addoption(parser):
    parser.addoption('--bench-scale', default='test',
                     choices=sorted(SCALES),
                     help='benchmark sample scale')


def pytest_configure(config):
    # CPU unless the run EXPLICITLY opts into the TPU with
    # BENCH_PLATFORM=tpu. The ambient environment exports
    # JAX_PLATFORMS=axon (the sitecustomize does, not the user), so
    # keying on JAX_PLATFORMS would block collection on a wedged
    # tunnel — the round-4 failure mode this guard exists for.
    import jax
    if os.environ.get('BENCH_PLATFORM', 'cpu') != 'tpu':
        jax.config.update('jax_platforms', 'cpu')


@pytest.fixture(scope='session')
def sample(request):
    """The benchmark sample scale (reference BenchmarkingSample)."""
    name = request.config.getoption('--bench-scale')
    s = dict(SCALES[name])
    s['name'] = name
    return s


@pytest.fixture
def benchmark(request):
    """``with benchmark('Data'): ...`` labelled phase timer; results
    land in $BENCH_DIR/<test_name>.json (reference timing blocks,
    benchmarks/test_fftpower.py:7-19)."""
    records = {}

    @contextlib.contextmanager
    def timer(name):
        t0 = time.time()
        yield
        records[name] = round(time.time() - t0, 4)

    yield timer

    if records:
        outdir = os.environ.get('BENCH_DIR', '.bench_results')
        os.makedirs(outdir, exist_ok=True)
        path = os.path.join(outdir, request.node.name + '.json')
        with open(path, 'w') as f:
            json.dump({'test': request.node.name, 'phases': records,
                       'at': time.strftime('%Y-%m-%dT%H:%M:%SZ',
                                           time.gmtime())}, f)
