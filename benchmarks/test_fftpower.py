"""FFTPower benchmark (reference benchmarks/test_fftpower.py:7-19):
LogNormalCatalog data phase + mode='2d' algorithm phase."""

import numpy as np


def test_fftpower(sample, benchmark):
    from nbodykit_tpu.lab import (LogNormalCatalog, LinearPower,
                                  FFTPower)
    from nbodykit_tpu.cosmology import Planck15

    with benchmark('Data'):
        Plin = LinearPower(Planck15, redshift=0.55,
                           transfer='EisensteinHu')
        nbar = sample['N'] / sample['BoxSize'] ** 3
        cat = LogNormalCatalog(Plin=Plin, nbar=nbar,
                               BoxSize=sample['BoxSize'],
                               Nmesh=sample['Nmesh'], bias=2.0, seed=42)

    with benchmark('Algorithm'):
        r = FFTPower(cat, mode='2d', Nmesh=sample['Nmesh'],
                     kmin=0.001, Nmu=10)
        assert np.isfinite(np.asarray(r.power['power'].real)).any()
