#!/usr/bin/env bash
# Smoke check: the diagnostics self-check (round-trips a trace file,
# including a simulated killed writer) plus the tier-1 fast subset of
# the suites covering the instrumented hot paths.  Intended as the
# cheap pre-push / CI gate; the full fast tier is ROADMAP.md's tier-1
# command.
#
#   scripts/smoke.sh            # default fast subset (~2-3 min warm)
#   SMOKE_PYTEST_ARGS='-x -k paint' scripts/smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== diagnostics self-check =="
python -m nbodykit_tpu.diagnostics --self-check

# the doctor's self-check verdict block (the module form works without
# installing the nbodykit-tpu-doctor console script)
echo "== doctor: self-check =="
python -m nbodykit_tpu.diagnostics --doctor --self-check-only

# bench-record gate: a malformed committed BENCH_r*.json fails here;
# stale cache replays / regressions print WARN verdicts but pass
echo "== doctor: bench regression gate =="
python -m nbodykit_tpu.diagnostics --regress .

# shard-safety lint gate: any finding not grandfathered in the
# committed lint_baseline.json fails the smoke run (the module form
# works without installing the nbodykit-tpu-lint console script).
# Since nbkl v2 the surface includes bench.py and the interprocedural
# NBK103/NBK5xx analyses run as part of the same gate.
echo "== shard-safety lint gate =="
python -m nbodykit_tpu.lint --baseline lint_baseline.json \
    nbodykit_tpu/ tests/_multihost_worker.py bench.py

# machine-readable per-family counts: the gate consumes the --stats
# JSON so a new finding in ANY family (incl. NBK103/NBK5xx) fails
# loudly with the per-family split, and regress.py records the same
# shape per round in BENCH_HISTORY.json
echo "== lint stats gate (per-family JSON) =="
python -m nbodykit_tpu.lint --stats --baseline lint_baseline.json \
    nbodykit_tpu/ tests/_multihost_worker.py bench.py | python -c '
import json, sys
stats = json.load(sys.stdin)
assert stats["gate"] == "OK", stats
assert stats["total"]["new"] == 0, stats
fams = stats["families"]
missing = {"NBK1", "NBK2", "NBK3", "NBK4", "NBK5",
           "NBK6", "NBK7", "NBK8"} - set(fams)
assert not missing, "family axis missing: %s" % missing
# NBK6xx/NBK7xx/NBK8xx were triaged in-PR (fixes + audited pragmas),
# so the budget for BOTH columns is zero: nothing new may appear and
# nothing may ever be grandfathered into the baseline for these
# families
for fam in ("NBK6", "NBK7", "NBK8"):
    assert fams[fam]["new"] == 0, (fam, fams[fam])
    assert fams[fam]["baselined"] == 0, (fam, fams[fam])
print("lint stats OK: " + "  ".join(
    "%s=%d+%d" % (k, v["new"], v["baselined"])
    for k, v in sorted(fams.items())))
'

# bounded symbolic-peak report for the north-star 1024^3 config
# (bench staged ladder + the dfft lowmem drivers): proves the
# documented buffer contracts still derive from the source, and that
# the donated staged chain stays inside the v5e budget while only the
# (staged-gated) fused pipeline exceeds it
echo "== memory report: 1024^3 north-star config (bounded) =="
python -m nbodykit_tpu.lint --memory-report --nmesh 1024 \
    --npart 1e8 bench.py nbodykit_tpu/parallel/dfft.py | python -c '
import sys
text = sys.stdin.read()
sys.stdout.write(text)
assert "OVER BUDGET" in text, "fused pipeline should exceed budget"
for fn in ("run_once", "rfftn_single_lowmem"):
    line = next(l for l in text.splitlines() if fn in l)
    assert "OVER BUDGET" not in line, (
        "staged/lowmem chain exceeded the budget: " + line)
'

# pencil branch of the memory model (docs/PERF.md): the documented
# 2-buffer eager contract (stage-2 donates stage-1) must keep pricing
# the north-star config — a drift between PENCIL_BUFFERS and the plan
# fails here, not on chip
echo "== memory plan: pencil buffer contract (1024^3, 8 dev) =="
python -c '
from nbodykit_tpu.parallel.dfft import PENCIL_BUFFERS
from nbodykit_tpu.pmesh import memory_plan
plan = memory_plan(1024, int(1e8), ndevices=8, fft_decomp="pencil")
assert plan["fft_pencil_buffers"] == PENCIL_BUFFERS == 2, plan
assert plan["fft_pencil"] == "2x4", plan
assert plan["fft_pencil_pad"] >= 1.0, plan
slab = memory_plan(1024, int(1e8), ndevices=8)
assert plan["fft_workspace"] >= slab["fft_workspace"], (plan, slab)
print("pencil plan OK: %s buffers=%d pad=%.4f fft_ws=%.2f GB" % (
    plan["fft_pencil"], plan["fft_pencil_buffers"],
    plan["fft_pencil_pad"], plan["fft_workspace"] / 2**30))
'

# pencil dist_rfftn end-to-end gate: a 4x2 pencil transform at
# mesh128 must match the slab path and round-trip through c2r at
# double precision — the two group transposes run for real on the
# 8-device CPU mesh
echo "== pencil FFT roundtrip gate (mesh128, 4x2) =="
python -c '
from nbodykit_tpu._jax_compat import set_cpu_devices
set_cpu_devices(8)
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
import jax.numpy as jnp
from nbodykit_tpu.parallel import dfft
from nbodykit_tpu.parallel.runtime import cpu_mesh, pencil_mesh
x = jnp.asarray(np.random.RandomState(7).standard_normal(
    (128, 128, 128)), jnp.float64)
pm = pencil_mesh(4, 2)
y = dfft.dist_rfftn(x, pm)
slab = dfft.dist_rfftn(x, cpu_mesh())
np.testing.assert_allclose(np.asarray(y), np.asarray(slab),
                           atol=1e-10)
back = dfft.dist_irfftn(y, 128, pm)
err = float(jnp.max(jnp.abs(back - x)))
assert err < 1e-10, err
print("pencil roundtrip OK: mesh128 4x2, max|irfftn(rfftn(x))-x| "
      "= %.3e" % err)
'

# halved-bytes precision gate (docs/PERF.md): a mesh64 FFTPower with
# bf16 mesh storage AND bf16 all_to_all payloads on the 8-device CPU
# mesh must stay inside the asserted P(k) budget vs the full-width
# oracle up to k_Nyquist/2, with identical mode counts — the bounded
# form of tests/test_precision.py, run on every smoke
echo "== precision gate (mesh64, bf16 mesh + bf16 a2a) =="
python -c '
from nbodykit_tpu._jax_compat import set_cpu_devices
set_cpu_devices(8)
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
import nbodykit_tpu
from nbodykit_tpu.lab import ArrayCatalog, FFTPower
from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh
NMESH, BOX = 64, 200.0
KMIN, DK = 0.31 * (2 * np.pi / BOX), 2.6718 * (2 * np.pi / BOX)
pos = np.random.RandomState(42).uniform(0, BOX, (10000, 3))
def pk(**opts):
    with use_mesh(cpu_mesh()):
        with nbodykit_tpu.set_options(**opts):
            cat = ArrayCatalog({"Position": pos}, BoxSize=BOX)
            r = FFTPower(cat, mode="1d", Nmesh=NMESH, kmin=KMIN, dk=DK)
    return (np.asarray(r.power["k"], "f8"),
            np.asarray(r.power["power"].real, "f8"),
            np.asarray(r.power["modes"], "f8"))
k0, p0, m0 = pk(mesh_dtype="f4", a2a_compress="none")
k, p, m = pk(mesh_dtype="bf16", a2a_compress="bf16")
np.testing.assert_array_equal(m, m0)
sel = (m0 > 0) & np.isfinite(p0) & (k0 <= 0.5 * np.pi * NMESH / BOX)
err = float((np.abs(p[sel] - p0[sel]) / np.abs(p0[sel]).mean()).max())
assert err < 2e-2, "P(k) budget blown: %.3e" % err
print("precision gate OK: bf16 mesh + bf16 a2a, max P(k) rel err "
      "%.3e < 2e-2 (%d bins <= k_Nyq/2)" % (err, int(sel.sum())))
'

# autotuner gates (docs/TUNE.md): the bounded --dry-run proves the
# deterministic trial plan still builds without touching a device —
# and that every multi-device fft trial races BOTH decompositions
# (chunk-laddered slab + the pencil candidate) under a
# factorization-suffixed shape class; --validate fails the smoke run
# on a malformed committed TUNE_CACHE.json (a broken database must
# never silently steer dispatch)
echo "== tune: dry-run plan + cache validation gate =="
python -m nbodykit_tpu.tune --dry-run --devices 8 | python -c '
import json, sys
plan = json.load(sys.stdin)["plan"]
ffts = [p for p in plan if p["op"] == "fft"]
assert ffts, "no fft trials in the plan"
for p in ffts:
    cands = p["candidates"]
    assert any(c.startswith("chunk") for c in cands), (
        "slab chunk ladder missing: %r" % cands)
    assert any(c.startswith("pencil") for c in cands), (
        "pencil decomposition candidate missing: %r" % cands)
    assert "-g" in p["shape_class"], (
        "factorization suffix missing: %r" % p["shape_class"])
    # halved-bytes wire candidates (docs/PERF.md): every multi-device
    # fft trial must race both compressed payloads against full-width
    assert "slab-a2a-bf16" in cands and "slab-a2a-int16" in cands, (
        "a2a compression candidates missing: %r" % cands)
paints = [p for p in plan if p["op"] == "paint"]
assert paints, "no paint trials in the plan"
for p in paints:
    assert "scatter-bf16" in p["candidates"], (
        "bf16 mesh candidate missing: %r" % p["candidates"])
# the bispectrum estimator race (docs/BISPECTRUM.md): every bspec
# trial must pit the FFT path against the direct pairblock tiles —
# the crossover is measured, never guessed
bspecs = [p for p in plan if p["op"] == "bspec"]
assert bspecs, "no bspec trials in the plan"
for p in bspecs:
    cands = p["candidates"]
    assert "fft" in cands, "fft estimator missing: %r" % cands
    assert any(c.startswith("direct-tile") for c in cands), (
        "direct pairblock candidates missing: %r" % cands)
print("tune plan OK: fft candidates " + " ".join(ffts[0]["candidates"])
      + " @ " + " ".join(p["shape_class"] for p in ffts)
      + "; bspec candidates " + " ".join(bspecs[0]["candidates"]))
'
python -m nbodykit_tpu.tune --validate

# paint candidate gate (docs/PERF.md): every registered paint
# candidate at a bounded CPU shape (mesh128/1e5, 2 reps) must lower,
# run and deposit finite mass — CI catches a candidate that stops
# lowering before a hardware window wastes its budget on it. Bench
# stdout may carry setup noise, so only the last line is parsed.
echo "== paint candidate gate (mesh128/1e5, all candidates) =="
python bench.py --paint-all 128 100000 2 | python -c '
import json, math, sys
recs = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert recs, "no paint candidates registered"
bad = {n: r["error"] for n, r in recs.items() if "error" in r}
assert not bad, "candidates raised: %r" % bad
for name, rec in sorted(recs.items()):
    assert rec["value"] > 0, (name, rec)
    assert math.isfinite(rec["mass_sum"]) and rec["mass_sum"] > 0, \
        (name, rec["mass_sum"])
print("paint gate OK: " + "  ".join(
    "%s=%.3fs" % (n, r["value"])
    for n, r in sorted(recs.items(), key=lambda kv: kv[1]["value"])))
'

# fault-injected resume smoke (docs/RESILIENCE.md): a 2-rep CPU bench
# is SIGKILLed entering rep 2 by the fault harness, then relaunched —
# the relaunch must resume from the checkpoint and flush one complete
# record stamped resumed: true. This rehearses the round-5 evidence
# loss end to end on every smoke run.
echo "== fault-injected kill/resume smoke =="
SMOKE_TMP=$(mktemp -d)
trap 'rm -rf "$SMOKE_TMP"' EXIT
smoke_env=(env JAX_PLATFORMS=cpu BENCH_REPS=2 BENCH_PHASES=0
           BENCH_STAGED_PATH="$SMOKE_TMP/STAGED.json"
           BENCH_DETAIL_PATH="$SMOKE_TMP/DETAIL.json"
           BENCH_CKPT_DIR="$SMOKE_TMP/CKPT"
           BENCH_TRACE_DIR="$SMOKE_TMP/TRACE")
rc=0
"${smoke_env[@]}" NBKIT_FAULTS='bench.rep@2:kill' \
    python bench.py --config 32 2000 || rc=$?
[ "$rc" -eq 137 ] || { echo "expected SIGKILL (137), got rc=$rc"; exit 1; }
"${smoke_env[@]}" python bench.py --config 32 2000 > "$SMOKE_TMP/rec.json"
python - "$SMOKE_TMP" <<'EOF'
import json, os, sys
tmp = sys.argv[1]
rec = json.loads(open(os.path.join(tmp, 'rec.json')).read().strip().splitlines()[-1])
assert rec.get('resumed') is True, rec
assert rec.get('value', -1) > 0 and rec.get('unit') == 's', rec
assert not [f for f in os.listdir(os.path.join(tmp, 'CKPT'))
            if f.endswith('.ckpt.json')], 'checkpoint not consumed'
print('resume smoke OK: %(metric)s resumed -> %(value)s s' % rec)
EOF

# multi-tenant serve gate (docs/SERVING.md): a 24-request synthetic
# trace with a mid-request tunnel death injected at the 3rd attempt —
# exactly one request retries (batching disabled so the fault lands on
# a single tenant), nothing is lost, every submission gets a structured
# verdict, p99 is recorded
echo "== serve trace gate (24 req, injected fault) =="
env JAX_NUM_CPU_DEVICES=2 \
    NBKIT_FAULTS='serve.request.attempt@3:unavailable' \
    python bench.py --serve-trace 24 1 1 0 > "$SMOKE_TMP/serve.json"
python - "$SMOKE_TMP" <<'EOF'
import json, os, sys
rec = json.loads(open(os.path.join(
    sys.argv[1], 'serve.json')).read().strip().splitlines()[-1])
assert rec['lost'] == 0, rec
assert rec['retried'] == 1, rec
assert rec['p99_s'] > 0, rec
resolved = (rec['completed'] + rec['rejected'] + rec['evicted']
            + rec['failed'])
assert resolved == rec['submitted'], rec
assert rec['faults_injected'], rec
print('serve gate OK: %(completed)d/%(submitted)d completed, '
      'retried=%(retried)d lost=%(lost)d p99=%(p99_s).3fs' % rec)
EOF

# ingestion plane gate (docs/INGEST.md): a small on-disk catalog is
# served twice via data_ref on a 2-device sub-mesh — both requests
# complete with bit-equal spectra and the second rides the worker's
# content-addressed CatalogCache (ingestion paid once, nothing lost)
echo "== ingest data_ref gate (2 requests, 1 cache hit) =="
python - "$SMOKE_TMP" <<'EOF'
import os, sys
import numpy as np
from nbodykit_tpu._jax_compat import set_cpu_devices
set_cpu_devices(2)
import jax
jax.config.update('jax_enable_x64', True)
import nbodykit_tpu
from nbodykit_tpu.serve import COMPLETED, AnalysisRequest, AnalysisServer
path = os.path.join(sys.argv[1], 'smoke_catalog.bin')
np.random.RandomState(11).uniform(
    0.0, 100.0, (2048, 3)).astype('f4').tofile(path)
ref = {'path': path, 'format': 'binary',
       'columns': {'Position': 'Position'},
       'options': {'dtype': [('Position', ('f4', 3))]}}
with nbodykit_tpu.set_options(ingest_chunk_rows=1024), \
        AnalysisServer(per_task=2, max_queue=4) as srv:
    r1 = srv.wait(srv.submit(AnalysisRequest(
        nmesh=32, data_ref=ref, deadline_s=600.0)))
    r2 = srv.wait(srv.submit(AnalysisRequest(
        nmesh=32, data_ref=ref, deadline_s=600.0)))
    summary = srv.summary()
assert r1.status == COMPLETED and r2.status == COMPLETED, (r1, r2)
np.testing.assert_array_equal(np.asarray(r1.y), np.asarray(r2.y))
assert summary['ingest_requests'] == 2, summary
assert summary['ingest_cache_hits'] == 1, summary
assert summary['lost'] == 0, summary
print('ingest gate OK: 2 data_ref requests completed, 1 cache hit, '
      'bit-equal P(k), lost=0')
EOF

# data-integrity gate (docs/INTEGRITY.md): a mesh64 FFT bench under
# integrity='cheap' with one stuck-at-one corruption injected into an
# all_to_all payload — the wire checksum must catch it, the supervisor
# retries once against the strike ledger, and the record is stamped
# integrity: {violations: 1, retried: 1}
echo "== integrity gate (mesh64, injected a2a corruption) =="
env JAX_NUM_CPU_DEVICES=8 NBKIT_FAULTS='a2a.payload@1:corrupt' \
    python bench.py --integrity 64 100000 2 > "$SMOKE_TMP/integ.json"
python - "$SMOKE_TMP" <<'EOF'
import json, os, sys
rec = json.loads(open(os.path.join(
    sys.argv[1], 'integ.json')).read().strip().splitlines()[-1])
assert rec.get('integrity') == {'violations': 1, 'retried': 1}, rec
assert rec.get('value', -1) > 0 and rec.get('unit') == 's', rec
print('integrity gate OK: 1 injected corruption caught at %s, '
      'retried clean, overhead %.1f%%' % (
          ','.join(rec.get('violation_sites', ['?'])),
          100.0 * rec.get('overhead', 0.0)))
EOF

# shadow-verification gate (docs/INTEGRITY.md): a seeded request with
# verify=True is re-executed on the OTHER sub-mesh after completing —
# the uncompressed program must come back bit-identical, proving two
# disjoint device groups agree on the full pipeline
echo "== shadow verification gate (verify=True, 2 sub-meshes) =="
python - <<'EOF'
from nbodykit_tpu._jax_compat import set_cpu_devices
set_cpu_devices(8)
import jax
jax.config.update('jax_enable_x64', True)
from nbodykit_tpu.serve import COMPLETED, AnalysisRequest, AnalysisServer
with AnalysisServer(per_task=4) as srv:
    assert len(srv.meshes) >= 2, srv.meshes
    r = srv.wait(srv.submit(AnalysisRequest(
        nmesh=32, npart=2000, seed=3, verify=True, deadline_s=600.0)))
    summary = srv.summary()
assert r.status == COMPLETED, r
assert summary['shadow_verified'] == 1, summary
assert summary['shadow_mismatch'] == 0, summary
print('shadow gate OK: 1 request shadow-verified bit-identical '
      'across sub-meshes, 0 mismatches')
EOF

# forward-model gate (docs/FORWARD.md): the differentiable pipeline
# must stay differentiable on every smoke run — a bounded 64^3 mesh /
# 1e4-particle KDK step is checked against a central finite difference
# (eps below the CIC kink noise at f8), then one Forward request rides
# the serve plane end to end: admitted with the reverse-pass memory
# branch, completed, nothing lost
echo "== forward gate (64^3/1e4 grad check + 1-request serve) =="
python - <<'EOF'
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_enable_x64', True)
import jax.numpy as jnp
from nbodykit_tpu.forward import ForwardModel, make_loss
model = ForwardModel(64, 22 ** 3, BoxSize=1000.0, pm_steps=1,
                     dtype='f8')
truth = model.linear_modes(0)
obs = jax.jit(model.density)(truth)
loss = make_loss(model, obs, noise_std=0.1)
w0 = model.lattice.c2r(model.lattice.generate_whitenoise(1)) * 0.05
g = jax.jit(jax.grad(loss))(w0)
d = model.lattice.c2r(model.lattice.generate_whitenoise(2))
d = d / jnp.sqrt(jnp.sum(d * d))
eps = 1e-6
lj = jax.jit(loss)
fd = (float(lj(w0 + eps * d)) - float(lj(w0 - eps * d))) / (2 * eps)
dot = float(jnp.sum(g * d))
rel = abs(fd - dot) / max(abs(fd), 1e-300)
assert rel < 1e-4, "grad check VIOLATED: fd=%r grad=%r rel=%.3e" % (
    fd, dot, rel)
print('forward grad OK: mesh64/n%d kdk, |fd-grad|/|fd| = %.3e'
      % (model.npart, rel))
EOF
python - <<'EOF'
from nbodykit_tpu._jax_compat import set_cpu_devices
set_cpu_devices(8)
import jax
jax.config.update('jax_enable_x64', True)
import numpy as np
from nbodykit_tpu.serve import COMPLETED, AnalysisRequest, AnalysisServer
with AnalysisServer(per_task=4) as srv:
    r = srv.wait(srv.submit(AnalysisRequest(
        algorithm='Forward', nmesh=16, npart=8 ** 3, pm_steps=1,
        seed=5, deadline_s=600.0)))
    summary = srv.summary()
assert r.status == COMPLETED, r
y = np.asarray(r.y)
assert np.isfinite(y).all() and np.abs(y).sum() > 0, y
assert summary['lost'] == 0, summary
print('forward serve OK: 1 Forward request completed '
      '(mesh16/n512 x1 step), lost=0')
EOF

# bispectrum gate (docs/BISPECTRUM.md): the Scoccimarro FFT estimator
# at mesh 16 must match a brute-force numpy oracle on the equilateral
# diagonal — every closed (mod-16) within-shell mode triangle summed
# directly from the full c2c spectrum — with bit-exact triangle
# counts; then one Bispectrum request rides the serve plane end to
# end: admitted under the 3-shell-field pricing branch, completed
# with finite shells, nothing lost
echo "== bispectrum gate (mesh16 equilateral oracle + serve) =="
python - <<'EOF'
from nbodykit_tpu._jax_compat import set_cpu_devices
set_cpu_devices(8)
import jax
jax.config.update('jax_enable_x64', True)
import numpy as np
import jax.numpy as jnp
from nbodykit_tpu.algorithms.bispectrum import fft_bispectrum
from nbodykit_tpu.pmesh import ParticleMesh
N, L, nbins = 16, 100.0, 3
pm = ParticleMesh(Nmesh=N, BoxSize=L, dtype='f8')
real = np.random.RandomState(5).standard_normal((N, N, N))
B, ntri = fft_bispectrum(pm, pm.r2c(jnp.asarray(real)), nbins)
dk = np.fft.fftn(real) / N ** 3
fx = np.fft.fftfreq(N, 1.0 / N).astype(int)
qx, qy, qz = np.meshgrid(fx, fx, fx, indexing='ij')
q = np.stack([qx, qy, qz], -1).reshape(-1, 3)
isq = (q ** 2).sum(1)
dflat = dk.reshape(-1)
for b in range(nbins):
    lo2, hi2 = (b + 1) ** 2, (b + 2) ** 2
    qs = q[(isq >= lo2) & (isq < hi2)]
    ds = dflat[(isq >= lo2) & (isq < hi2)]
    q3 = (-(qs[:, None, :] + qs[None, :, :])) % N
    s3 = (((q3 + N // 2) % N - N // 2) ** 2).sum(-1)
    idx = (q3[..., 0] * N + q3[..., 1]) * N + q3[..., 2]
    m = (s3 >= lo2) & (s3 < hi2)
    S = (ds[:, None] * ds[None, :] * dflat[idx])[m].sum()
    cnt = int(m.sum())
    assert int(ntri[b, b, b]) == cnt, (b, ntri[b, b, b], cnt)
    want = L ** 6 * S.real / cnt
    rel = abs(float(B[b, b, b]) - want) / max(abs(want), 1e-300)
    assert rel < 1e-6, (b, float(B[b, b, b]), want, rel)
print('bispectrum oracle OK: mesh16 equilateral, %d shells '
      'bit-exact ntri, B rel err < 1e-6' % nbins)
EOF
python - <<'EOF'
from nbodykit_tpu._jax_compat import set_cpu_devices
set_cpu_devices(8)
import jax
jax.config.update('jax_enable_x64', True)
import numpy as np
from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh
from nbodykit_tpu.serve import COMPLETED, AnalysisRequest, AnalysisServer
with use_mesh(cpu_mesh(1)):
    srv = AnalysisServer(per_task=1)
with srv:
    r = srv.wait(srv.submit(AnalysisRequest(
        algorithm='Bispectrum', nmesh=16, npart=4000, nbins=3,
        seed=9, deadline_s=600.0)), timeout=600)
    summary = srv.summary()
assert r.status == COMPLETED, r
y = np.asarray(r.y)
assert np.isfinite(y).all() and y.shape == (3,), y
assert np.asarray(r.nmodes).min() > 0, r.nmodes
assert summary['lost'] == 0, summary
print('bispectrum serve OK: 1 Bispectrum request completed '
      '(mesh16, 3 shells, finite B), lost=0')
EOF

# region gate (docs/SERVING.md "Region"): a two-fleet router trace
# with a third fleet joining mid-trace — the bench asserts the whole
# region posture in one shot: >=1 content-addressed result-cache hit
# (repeat slices of the trace), >=1 structured spill redirect (the
# closed-loop slam overflows spill_depth), the elastic join sealed
# with reformed_from/to stamps, fair share holding under the bulk
# tenant's priority-2 flood (throttled > 0, starved == 0), cached
# bytes bit-identical to a fresh recomputation, and zero lost
echo "== region gate (40 req, 2 fleets + mid-trace join) =="
env JAX_NUM_CPU_DEVICES=2 \
    python bench.py --region-trace 40 2 1 0 > "$SMOKE_TMP/region.json"
python - "$SMOKE_TMP" <<'EOF'
import json, os, sys
rec = json.loads(open(os.path.join(
    sys.argv[1], 'region.json')).read().strip().splitlines()[-1])
assert rec['lost'] == 0, rec
assert rec['result_hits'] >= 1, rec
assert rec['spills'] >= 1, rec
assert rec['joins'] == 1, rec
assert rec['reformed_from'] == 2 and rec['reformed_to'] == 3, rec
assert rec['throttled'] > 0, rec
assert rec['starved'] == 0, rec
assert rec['unverified_as_verified'] == 0, rec
assert rec['cache_bit_identical'] is True, rec
assert 'error' not in rec, rec
print('region gate OK: %(completed)d/%(submitted)d completed over '
      '%(fleet_count)d fleets, hits=%(result_hits)d '
      'spills=%(spills)d joins=%(joins)d throttled=%(throttled)d '
      'starved=%(starved)d lost=%(lost)d' % rec)
EOF

# the rule-tree-produced PartitionSpecs cross shard_map boundaries in
# the paint path; the sharding-flow analyses must stay clean over the
# whole surface with nothing new and nothing grandfathered (the
# NBK6 zero-budget policy from the stats gate, enforced standalone so
# an ingest-plane spec bug fails even if run outside full smoke)
echo "== ingest sharding-flow gate (NBK6xx clean) =="
python -m nbodykit_tpu.lint --select NBK6 nbodykit_tpu/ bench.py
python -m nbodykit_tpu.lint --shard-report nbodykit_tpu/ingest/ \
    nbodykit_tpu/pmesh.py

# the threaded control plane (serve workers, region pacer, exporter
# httpd, fleet monitor, trace heartbeat) must stay free of lock-order
# inversions, cross-thread races and blocking-under-lock — the NBK8
# zero-budget policy from the stats gate, enforced standalone over
# the full tree; the lock report doubles as the human-readable map
# of every lock identity and its acquiring threads
echo "== host-concurrency gate (NBK8xx clean) =="
python -m nbodykit_tpu.lint --select NBK8 nbodykit_tpu/ bench.py
python -m nbodykit_tpu.lint --lock-report nbodykit_tpu/

# fleet survivability gate (docs/RESILIENCE.md): a 2-process gloo
# fleet has rank 1 SIGKILLed entering rep 2 — rank 0's live monitor
# must detect the dead peer and exit DEAD_RANK_EXIT (76) instead of
# wedging in the collective, leaving a sealed 2-rank manifest; the
# 1-process relaunch re-forms the mesh, repartitions the surviving
# shards and resumes from the seal (reformed_from: 2)
echo "== fleet kill/detect/re-form/resume gate (2 proc -> 1) =="
fleet_env=(env JAX_PLATFORMS=cpu
           NBKIT_DIAGNOSTICS="$SMOKE_TMP/FLEET_TRACE"
           NBKIT_DIAGNOSTICS_HEARTBEAT=0.25
           NBKIT_FLEET_DIR="$SMOKE_TMP/FLEET_CKPT"
           NBKIT_FLEET_RECORD="$SMOKE_TMP/fleet_rec.json"
           NBKIT_FLEET_GAP_S=1.5)
mkdir -p "$SMOKE_TMP/FLEET_CKPT"
rc0=0; rc1=0
"${fleet_env[@]}" NBKIT_FAULTS='rank1@bench.rep@2:sigkill' \
    python tests/_multihost_worker.py 127.0.0.1:12377 2 0 fleet \
    > "$SMOKE_TMP/fleet0.log" 2>&1 &
pid0=$!
"${fleet_env[@]}" NBKIT_FAULTS='rank1@bench.rep@2:sigkill' \
    python tests/_multihost_worker.py 127.0.0.1:12377 2 1 fleet \
    > "$SMOKE_TMP/fleet1.log" 2>&1 &
pid1=$!
wait "$pid0" || rc0=$?
wait "$pid1" || rc1=$?
[ "$rc0" -eq 76 ] || { echo "rank 0: expected DEAD_RANK_EXIT (76)," \
    "got rc=$rc0"; tail -40 "$SMOKE_TMP/fleet0.log"; exit 1; }
[ "$rc1" -eq 137 ] || { echo "rank 1: expected SIGKILL (137), got" \
    "rc=$rc1"; tail -40 "$SMOKE_TMP/fleet1.log"; exit 1; }
"${fleet_env[@]}" python tests/_multihost_worker.py none 1 0 fleet \
    > "$SMOKE_TMP/fleet_resume.log" 2>&1 \
    || { tail -40 "$SMOKE_TMP/fleet_resume.log"; exit 1; }
python - "$SMOKE_TMP" <<'EOF'
import json, os, sys
tmp = sys.argv[1]
rec = json.load(open(os.path.join(tmp, 'fleet_rec.json')))
assert rec.get('resumed') is True, rec
assert rec.get('reformed_from') == 2 and rec.get('reformed_to') == 1, rec
assert rec.get('completed') == rec.get('reps'), rec
from nbodykit_tpu.diagnostics import read_trace
records, _ = read_trace(os.path.join(tmp, 'FLEET_TRACE'))
dead = [r for r in records if r.get('t') == 'span'
        and r.get('name') == 'resilience.fleet.dead_rank']
assert dead, 'no dead-rank event in the monitor trace'
print('fleet gate OK: dead rank detected, mesh re-formed '
      '%(reformed_from)d -> %(reformed_to)d, resumed at rep '
      '%(resumed_reps)d' % rec)
EOF

# observability gate (docs/OBSERVABILITY.md): a 24-request region
# trace with the live export plane enabled — every request must
# render a fully linked orphan-free waterfall, the telemetry
# endpoint must scrape (Prometheus text with real per-fleet labels,
# SLO snapshot), and an injected preemption must seal the flight
# recorder next to the trace
echo "== observability gate (24-req region trace + export + flight) =="
env NBKIT_DIAGNOSTICS_SYNC=0 NBKIT_TRACE_EXEMPLAR=0.02 \
    JAX_NUM_CPU_DEVICES=2 python - "$SMOKE_TMP" <<'EOF'
import json, os, sys, urllib.request
import nbodykit_tpu
from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh
from nbodykit_tpu.serve import (AnalysisRequest, AnalysisServer,
                                QoSPolicy, Region, ResultCache,
                                ServiceClass)
from nbodykit_tpu.diagnostics import request_report
from nbodykit_tpu.diagnostics.analyze import load_processes
from nbodykit_tpu.diagnostics.export import ensure_exporter, \
    stop_exporter

tmp = sys.argv[1]
tracedir = os.path.join(tmp, 'obs_trace')
os.makedirs(tracedir, exist_ok=True)


def req(i, seed, deadline=300.0):
    return AnalysisRequest(algorithm='FFTPower', nmesh=16, npart=1000,
                           seed=seed, deadline_s=deadline,
                           request_id='obs-%03d' % i)


def fleet():
    with use_mesh(cpu_mesh(1)):
        return AnalysisServer(per_task=1)


qos = QoSPolicy(
    classes=[ServiceClass('interactive'),
             ServiceClass('bulk', rate=4.0, burst=1)],
    tenants={'bulk-sweep': 'bulk'}, default_class='interactive')
with nbodykit_tpu.set_options(diagnostics=tracedir,
                              telemetry_port=0):
    region = Region([('a', fleet()), ('b', fleet())],
                    result_cache=ResultCache(
                        os.path.join(tmp, 'obs_rcache')), qos=qos)
    exp = ensure_exporter()
    assert exp is not None, 'telemetry_port=0 started no exporter'
    tickets = []
    # 16 interactive (4 distinct shapes -> warm cache), 4 repeats
    # (result-cache hits / singleflight), 4 bulk (pacer-held)
    for i in range(16):
        tickets.append(region.submit(req(i, seed=100 + i % 4)))
    for i in range(16, 20):
        tickets.append(region.submit(req(i, seed=100 + i % 4)))
    for i in range(20, 24):
        tickets.append(region.submit(req(i, seed=200 + i),
                                     tenant='bulk-sweep'))
    results = [region.wait(t, timeout=300) for t in tickets]
    assert all(r is not None and r.status == 'completed'
               for r in results), \
        [getattr(r, 'status', None) for r in results]

    # scrape the export plane while the region is live
    text = urllib.request.urlopen(exp.url + '/metrics').read().decode()
    assert 'region_completed_total' in text, text[:400]
    assert 'region_fleet_load{fleet=' in text, text[:400]
    slo = json.loads(urllib.request.urlopen(exp.url + '/slo').read())
    assert 'region' in slo and slo['region']['verdict'] == 'OK', slo
    assert urllib.request.urlopen(exp.url + '/healthz').read() \
        == b'ok\n'

    summary = region.summary()
    region.shutdown()
    # injected preemption: the SIGTERM drain path must seal the
    # flight ring beside the trace
    region.router.fleets()[0].server.preempt(grace_s=2.0)
stop_exporter()

procs, torn = load_processes(tracedir)
assert torn == 0, torn
rep = request_report(procs)
assert rep['traces'] >= 24, rep['traces']
assert rep['complete'] == rep['traces'], rep['incomplete']
assert rep['orphan_spans'] == 0, rep['orphan_spans']
assert 'qos_hold' in rep['stage_totals_s'], rep['stage_totals_s']

dumps = [f for f in os.listdir(tracedir) if f.startswith('flight-')]
assert dumps, 'preemption sealed no flight dump'
body = json.load(open(os.path.join(tracedir, dumps[0])))
assert body['reason'].startswith('serve.preempt'), body['reason']
assert body['requests'], 'flight ring empty'
print('observability gate OK: %d/%d waterfalls complete, 0 orphans, '
      'slo %s, flight dump %s (%d entries)'
      % (rep['complete'], rep['traces'],
         summary['slo']['verdict'], dumps[0], len(body['requests'])))
EOF

echo "== tier-1 fast subset =="
python -m pytest \
    tests/test_diagnostics.py \
    tests/test_diagnostics_analyze.py \
    tests/test_resilience.py \
    tests/test_fleet.py \
    tests/test_tune.py \
    tests/test_serve.py \
    tests/test_region.py \
    tests/test_observability.py \
    tests/test_lint.py \
    tests/test_lint_concurrency.py \
    tests/test_lint_dataflow.py \
    tests/test_lint_shardflow.py \
    tests/test_lint_dtypeflow.py \
    tests/test_jax_compat.py \
    tests/test_pmesh.py \
    tests/test_pencil_fft.py \
    tests/test_paint_kernels.py \
    tests/test_fftpower.py \
    tests/test_forward.py \
    tests/test_bispectrum.py \
    tests/test_counted_exchange.py \
    tests/test_radix.py \
    tests/test_ingest.py \
    -q -m 'not slow' -p no:cacheprovider ${SMOKE_PYTEST_ARGS:-}

echo "smoke OK"
