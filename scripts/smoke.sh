#!/usr/bin/env bash
# Smoke check: the diagnostics self-check (round-trips a trace file,
# including a simulated killed writer) plus the tier-1 fast subset of
# the suites covering the instrumented hot paths.  Intended as the
# cheap pre-push / CI gate; the full fast tier is ROADMAP.md's tier-1
# command.
#
#   scripts/smoke.sh            # default fast subset (~2-3 min warm)
#   SMOKE_PYTEST_ARGS='-x -k paint' scripts/smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== diagnostics self-check =="
python -m nbodykit_tpu.diagnostics --self-check

# the doctor's self-check verdict block (the module form works without
# installing the nbodykit-tpu-doctor console script)
echo "== doctor: self-check =="
python -m nbodykit_tpu.diagnostics --doctor --self-check-only

# bench-record gate: a malformed committed BENCH_r*.json fails here;
# stale cache replays / regressions print WARN verdicts but pass
echo "== doctor: bench regression gate =="
python -m nbodykit_tpu.diagnostics --regress .

# shard-safety lint gate: any finding not grandfathered in the
# committed lint_baseline.json fails the smoke run (the module form
# works without installing the nbodykit-tpu-lint console script)
echo "== shard-safety lint gate =="
python -m nbodykit_tpu.lint --baseline lint_baseline.json \
    nbodykit_tpu/ tests/_multihost_worker.py

echo "== tier-1 fast subset =="
python -m pytest \
    tests/test_diagnostics.py \
    tests/test_diagnostics_analyze.py \
    tests/test_lint.py \
    tests/test_jax_compat.py \
    tests/test_pmesh.py \
    tests/test_fftpower.py \
    tests/test_counted_exchange.py \
    tests/test_radix.py \
    -q -m 'not slow' -p no:cacheprovider ${SMOKE_PYTEST_ARGS:-}

echo "smoke OK"
