"""Flagship benchmark: FFTPower wall-clock on the north-star config.

Target metric (BASELINE.json): FFTPower wallclock @ Nmesh=1024^3, 1e8
particles. The pipeline measured is the fused jitted program
paint -> rfft -> window compensation -> |delta_k|^2 -> (k, mu) binning —
the same work the reference does across pmesh C paint + pfft MPI FFT +
the project_to_basis slab loop (SURVEY.md §3.1).

Prints ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}

``vs_baseline`` is (estimated reference wallclock) / (ours) — >1 means
faster than the baseline. The reference publishes no absolute numbers
(BASELINE.md); we use a 30 s nominal for the dm_like-scale FFTPower on a
16-rank MPI node (the reference's example production config,
nersc/example-job.slurm), documented here so the denominator is stable
across rounds.

Robustness (round-2 hardening — the round-1 bench burned its whole
window on a wedged axon tunnel):
- the orchestrator process NEVER imports jax; every probe/measurement
  runs in a subprocess with a hard timeout, so a wedged backend init
  cannot consume the window;
- a cheap backend health probe gates everything; if it fails we print a
  JSON line immediately (value -1) instead of timing out silently;
- configs run smallest-first so SOME number always exists, escalating
  to the north-star config; the largest successful config is reported;
- a paint-only microbenchmark is recorded to stderr and
  BENCH_DETAIL.json for kernel-level tracking.

Subcommands (internal):
    bench.py --probe                 backend sanity check
    bench.py --config N NPART [m]    one fftpower config, JSON on stdout
    bench.py --paint N NPART         paint-only microbench
    bench.py --autotune N NPART      pick paint kernel ('sort'|'scatter')
"""

import json
import os
import subprocess
import sys
import time

TOTAL_BUDGET_S = float(os.environ.get('BENCH_BUDGET_S', 1500))
PROBE_TIMEOUT_S = float(os.environ.get('BENCH_PROBE_TIMEOUT_S', 150))
NOMINAL_BASELINE_S = 30.0  # see module docstring


def _setup_jax():
    """Import jax safely under axon: honor an explicit cpu request the
    way __graft_entry__.py does (the sitecustomize overrides
    JAX_PLATFORMS/XLA_FLAGS env vars, so re-assert via jax.config)."""
    import re
    import jax
    if 'cpu' in os.environ.get('JAX_PLATFORMS', ''):
        jax.config.update('jax_platforms', 'cpu')
        m = re.search(r'xla_force_host_platform_device_count=(\d+)',
                      os.environ.get('XLA_FLAGS', ''))
        n = int(m.group(1)) if m else int(
            os.environ.get('JAX_NUM_CPU_DEVICES', '0') or 0)
        if n > 1:
            jax.config.update('jax_num_cpu_devices', n)
    return jax


def cmd_probe():
    jax = _setup_jax()
    import jax.numpy as jnp
    d = jax.devices()
    x = jnp.ones((128, 128))
    s = float((x @ x).sum())
    assert s == 128.0 * 128 * 128
    print(json.dumps({"platform": d[0].platform,
                      "kind": getattr(d[0], 'device_kind', '?'),
                      "n": len(d)}))
    return 0


def _bench_fftpower_fn(pm, Npart, resampler='cic', slab_chunks=16):
    """The fused pipeline with slab-chunked (k,mu) binning.

    Binning loops over chunks of the complex field's leading axis with a
    fori_loop so no full-mesh f32 temporaries (k2/mu/digitize indices)
    are ever live at once — at Nmesh=1024 the unchunked version needs
    ~6 extra 2.1 GB buffers, which does not fit v5e HBM alongside the
    FFT workspace.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    from nbodykit_tpu.ops.window import compensation_transfer

    Nmesh = int(pm.Nmesh[0])
    L = float(pm.BoxSize[0])
    kedges = np.arange(0.0, np.pi * Nmesh / L + np.pi / (L / 2.0),
                       2 * np.pi / L)
    Nx = len(kedges) - 1
    Nmu = 10
    nbins = (Nx + 2) * (Nmu + 2)
    x2edges = jnp.asarray(kedges.astype('f4') ** 2)
    muedges = jnp.asarray(np.linspace(-1, 1, Nmu + 1).astype('f4'))
    transfer = compensation_transfer(resampler, False)
    V = L ** 3

    N1c, N0c, nz = pm.shape_complex  # transposed complex layout
    assert N1c % slab_chunks == 0
    rows = N1c // slab_chunks

    kx_full, ky_full, kz_full = pm.k_list(dtype=jnp.float32)
    # ky is the leading axis of the transposed layout
    ky_flat = ky_full.reshape(-1)

    def fftpower(pos):
        n = pos.shape[0]
        field = pm.paint(pos, 1.0, resampler=resampler)
        field = field / (n / pm.Ntot)
        c = pm.r2c(field)
        w = pm.k_list(dtype=jnp.float32, circular=True)
        c = transfer(w, c)
        p3 = (jnp.abs(c) ** 2).astype(jnp.float32) * V
        p3 = p3.at[0, 0, 0].set(0.0)
        herm_z = pm.hermitian_weights(dtype=jnp.float32)  # (1,1,nz)

        def body(i, acc):
            Psum, Nsum = acc
            sl = jax.lax.dynamic_slice(p3, (i * rows, 0, 0),
                                       (rows, N0c, nz))
            ky = jax.lax.dynamic_slice(ky_flat, (i * rows,),
                                       (rows,)).reshape(rows, 1, 1)
            k2 = kx_full * kx_full + ky * ky + kz_full * kz_full
            kk = jnp.sqrt(k2)
            mu = jnp.where(kk == 0, 0.0,
                           kz_full / jnp.where(kk == 0, 1.0, kk))
            wgt = jnp.broadcast_to(herm_z, sl.shape).reshape(-1)
            dig = (jnp.digitize(k2.reshape(-1), x2edges) * (Nmu + 2)
                   + jnp.digitize(jnp.broadcast_to(mu, sl.shape)
                                  .reshape(-1), muedges)).astype(jnp.int32)
            Psum = Psum + jnp.bincount(dig, weights=sl.reshape(-1) * wgt,
                                       length=nbins)
            Nsum = Nsum + jnp.bincount(dig, weights=wgt, length=nbins)
            return Psum, Nsum

        init = (jnp.zeros(nbins, jnp.float32), jnp.zeros(nbins, jnp.float32))
        return jax.lax.fori_loop(0, slab_chunks, body, init)

    return fftpower


def _make_pos(jax, jnp, Npart, L, seed=7):
    pos = jax.random.uniform(jax.random.key(seed), (Npart, 3),
                             jnp.float32, 0.0, L)
    jax.block_until_ready(pos)
    return pos


def cmd_config(Nmesh, Npart, method='scatter', reps=3):
    jax = _setup_jax()
    import jax.numpy as jnp
    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh

    nbodykit_tpu.set_options(paint_method=method)
    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=1000.0, dtype='f4')
    pos = _make_pos(jax, jnp, Npart, 1000.0)
    fn = jax.jit(_bench_fftpower_fn(pm, Npart))
    t0 = time.time()
    out = fn(pos)
    jax.block_until_ready(out)
    compile_s = time.time() - t0
    t0 = time.time()
    for _ in range(reps):
        out = fn(pos)
    jax.block_until_ready(out)
    dt = (time.time() - t0) / reps
    print(json.dumps({
        "metric": "fftpower_wallclock_nmesh%d_npart%.0e" % (Nmesh, Npart),
        "value": round(dt, 4),
        "unit": "s",
        "vs_baseline": round(NOMINAL_BASELINE_S / dt, 2),
        "compile_s": round(compile_s, 1),
        "paint_method": method,
    }))
    return 0


def cmd_paint(Nmesh, Npart, method='scatter', reps=3):
    """Paint-only microbenchmark (the #1 perf risk, SURVEY §7)."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh

    nbodykit_tpu.set_options(paint_method=method)
    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=1000.0, dtype='f4')
    pos = _make_pos(jax, jnp, Npart, 1000.0)
    fn = jax.jit(lambda p: pm.paint(p, 1.0, resampler='cic'))
    jax.block_until_ready(fn(pos))
    t0 = time.time()
    for _ in range(reps):
        out = fn(pos)
    jax.block_until_ready(out)
    dt = (time.time() - t0) / reps
    print(json.dumps({
        "metric": "paint_wallclock_nmesh%d_npart%.0e_%s"
                  % (Nmesh, Npart, method),
        "value": round(dt, 4), "unit": "s",
        "mpart_per_s": round(Npart / dt / 1e6, 1),
    }))
    return 0


def cmd_autotune(Nmesh, Npart):
    jax = _setup_jax()
    import jax.numpy as jnp
    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh

    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=1000.0, dtype='f4')
    pos = _make_pos(jax, jnp, Npart, 1000.0)
    times = {}
    for method in ['sort', 'scatter']:
        try:
            with nbodykit_tpu.set_options(paint_method=method):
                f = jax.jit(lambda p: pm.paint(p, 1.0, resampler='cic'))
                jax.block_until_ready(f(pos))
                t0 = time.time()
                for _ in range(2):
                    out = f(pos)
                jax.block_until_ready(out)
                times[method] = (time.time() - t0) / 2
        except Exception as e:
            print("paint method %s failed: %s" % (method, str(e)[:120]),
                  file=sys.stderr)
            times[method] = float('inf')
    best = min(times, key=times.get)
    print(json.dumps({"best": best,
                      "times": {k: (round(v, 4) if v != float('inf')
                                    else None)
                                for k, v in times.items()}}))
    return 0


# ---------------------------------------------------------------------------
# orchestrator (no jax in this process)

def _run_sub(args, timeout):
    """Run a bench.py subcommand; return parsed last-line JSON or None."""
    cmd = [sys.executable, os.path.abspath(__file__)] + args
    t0 = time.time()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout)
    except subprocess.TimeoutExpired:
        print("[bench] %s TIMED OUT after %.0fs" % (args, timeout),
              file=sys.stderr)
        return None
    dt = time.time() - t0
    if r.stderr.strip():
        tail = r.stderr.strip().splitlines()[-8:]
        print("[bench] %s stderr tail: %s" % (args[0], " | ".join(tail)),
              file=sys.stderr)
    if r.returncode != 0:
        print("[bench] %s rc=%d (%.0fs)" % (args, r.returncode, dt),
              file=sys.stderr)
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith('{'):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def main():
    deadline = time.time() + TOTAL_BUDGET_S
    detail = {"probe": None, "autotune": None, "paint": [], "configs": []}

    def left():
        return deadline - time.time()

    probe = _run_sub(['--probe'], min(PROBE_TIMEOUT_S, left()))
    detail['probe'] = probe
    if probe is None:
        print(json.dumps({"metric": "fftpower_wallclock", "value": -1,
                          "unit": "s", "vs_baseline": 0,
                          "error": "backend probe failed/timed out"}))
        _dump_detail(detail)
        return 1
    print("[bench] backend: %s" % probe, file=sys.stderr)

    tune = _run_sub(['--autotune', '256', '2000000'], min(420, left()))
    detail['autotune'] = tune
    method = (tune or {}).get('best', 'scatter')
    print("[bench] paint method: %s (%s)" % (method, tune),
          file=sys.stderr)

    # paint microbench at a mid scale
    if left() > 240:
        p = _run_sub(['--paint', '512', '10000000', method],
                     min(420, left()))
        detail['paint'].append(p)
        print("[bench] paint micro: %s" % p, file=sys.stderr)

    # smallest-first ladder up to the north-star config; keep the last
    # success. The paint kernel is re-autotuned at each Nmesh scale (a
    # small-probe winner must not be forced on large configs — the sort
    # kernel's memory/cost profile changes with Nmesh/Npart), and a
    # failed config is retried once with the other kernel before
    # stopping escalation (on axon, a huge failed compile can wedge the
    # tunnel for everyone downstream).
    ladder = [
        (128, 100_000, 120),
        (256, 1_000_000, 180),
        (512, 10_000_000, 480),
        (1024, 10_000_000, 700),
        (1024, 100_000_000, 700),
    ]
    best = None
    tuned_at = 256
    for Nmesh, Npart, budget in ladder:
        if left() < budget * 0.5:
            print("[bench] skipping Nmesh=%d Npart=%d (%.0fs left)"
                  % (Nmesh, Npart, left()), file=sys.stderr)
            break
        if Nmesh > tuned_at and left() > budget:
            t = _run_sub(['--autotune', str(Nmesh),
                          str(min(Npart, 5_000_000))],
                         min(420, left() - budget * 0.5))
            if t is not None:
                method = t.get('best', method)
                tuned_at = Nmesh
                print("[bench] re-autotuned at Nmesh=%d: %s"
                      % (Nmesh, t), file=sys.stderr)
        res = _run_sub(['--config', str(Nmesh), str(Npart), method],
                       min(budget, left()))
        if res is None:
            other = 'sort' if method == 'scatter' else 'scatter'
            print("[bench] config Nmesh=%d Npart=%d failed with %s; "
                  "retrying with %s" % (Nmesh, Npart, method, other),
                  file=sys.stderr)
            if left() > budget * 0.5:
                res = _run_sub(['--config', str(Nmesh), str(Npart),
                                other], min(budget, left()))
        detail['configs'].append(res)
        if res is None:
            print("[bench] config Nmesh=%d Npart=%d failed; stopping "
                  "escalation" % (Nmesh, Npart), file=sys.stderr)
            break
        best = res
        print("[bench] ok: %s" % res, file=sys.stderr)

    _dump_detail(detail)
    if best is None:
        print(json.dumps({"metric": "fftpower_wallclock", "value": -1,
                          "unit": "s", "vs_baseline": 0,
                          "error": "no config succeeded"}))
        return 1
    out = {k: best[k] for k in ("metric", "value", "unit", "vs_baseline")}
    print(json.dumps(out))
    return 0


def _dump_detail(detail):
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               'BENCH_DETAIL.json'), 'w') as f:
            json.dump(detail, f, indent=1)
    except OSError:
        pass


if __name__ == '__main__':
    argv = sys.argv[1:]
    if not argv:
        sys.exit(main())
    if argv[0] == '--probe':
        sys.exit(cmd_probe())
    if argv[0] == '--config':
        sys.exit(cmd_config(int(argv[1]), int(argv[2]),
                            *(argv[3:4] or ['scatter'])))
    if argv[0] == '--paint':
        sys.exit(cmd_paint(int(argv[1]), int(argv[2]),
                           *(argv[3:4] or ['scatter'])))
    if argv[0] == '--autotune':
        sys.exit(cmd_autotune(int(argv[1]), int(argv[2])))
    print("unknown args: %r" % (argv,), file=sys.stderr)
    sys.exit(2)
