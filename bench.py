"""Flagship benchmark: FFTPower wall-clock on the north-star config.

Target metric (BASELINE.json): FFTPower wallclock @ Nmesh=1024^3, 1e8
particles. The pipeline measured is the fused jitted program
paint -> rfft -> window compensation -> |delta_k|^2 -> (k, mu) binning —
the same work the reference does across pmesh C paint + pfft MPI FFT +
the project_to_basis slab loop (SURVEY.md §3.1).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}

``vs_baseline`` is (estimated reference wallclock) / (ours) — >1 means
faster than the baseline. The reference publishes no absolute numbers
(BASELINE.md); we use a 30 s nominal for the dm_like-scale FFTPower on a
16-rank MPI node (the reference's example production config,
nersc/example-job.slurm), documented here so the denominator is stable
across rounds.

The benchmark auto-scales down if the device cannot fit the north-star
config (adaptive retry), reporting the achieved config in the metric
name.
"""

import json
import sys
import time

import numpy as np

NOMINAL_BASELINE_S = 30.0  # see module docstring


def autotune_paint(Nmesh=256, Npart=2_000_000):
    """Pick the faster local paint kernel ('scatter' vs 'sort') on this
    backend — TPU scatter-add serializes on collisions, while the sort
    path costs a big lax.sort; which wins is hardware-dependent."""
    import time as _t
    import jax
    import jax.numpy as jnp
    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh

    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=1000.0, dtype='f4')
    pos = jax.random.uniform(jax.random.key(1), (Npart, 3),
                             jnp.float32, 0.0, 1000.0)
    jax.block_until_ready(pos)
    times = {}
    for method in ['sort', 'scatter']:
        try:
            with nbodykit_tpu.set_options(paint_method=method):
                f = jax.jit(lambda p: pm.paint(p, 1.0,
                                               resampler='cic'))
                jax.block_until_ready(f(pos))  # compile
                t0 = _t.time()
                for _ in range(2):
                    out = f(pos)
                jax.block_until_ready(out)
                times[method] = (_t.time() - t0) / 2
        except Exception as e:
            print("paint method %s failed: %s" % (method, str(e)[:120]),
                  file=sys.stderr)
            times[method] = float('inf')
    best = min(times, key=times.get)
    print("paint autotune: %s  (%s)" % (best, {k: round(v, 4)
          for k, v in times.items()}), file=sys.stderr)
    return best


def run_config(Nmesh, Npart, resampler='cic', paint_method='scatter'):
    import jax
    import jax.numpy as jnp
    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh
    from nbodykit_tpu.ops.window import compensation_transfer

    nbodykit_tpu.set_options(paint_method=paint_method)
    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=1000.0, dtype='f4')
    pos = jax.random.uniform(jax.random.key(7), (Npart, 3), jnp.float32,
                             0.0, 1000.0)
    jax.block_until_ready(pos)

    kedges = np.arange(0.0, np.pi * Nmesh / 1000.0 + np.pi / 500.0,
                       2 * np.pi / 1000.0)
    Nx = len(kedges) - 1
    Nmu = 10
    muedges = np.linspace(-1, 1, Nmu + 1)
    x2edges = jnp.asarray(kedges.astype('f4') ** 2)
    muedges_j = jnp.asarray(muedges.astype('f4'))
    transfer = compensation_transfer(resampler, False)

    V = 1000.0 ** 3
    nbins = (Nx + 2) * (Nmu + 2)

    @jax.jit
    def fftpower(pos):
        field = pm.paint(pos, 1.0, resampler=resampler)
        nbar = Npart / pm.Ntot
        field = field / nbar
        c = pm.r2c(field)
        w = pm.k_list(dtype=jnp.float32, circular=True)
        c = transfer(w, c)
        p3 = (jnp.abs(c) ** 2).astype(jnp.float32) * V
        p3 = p3.at[0, 0, 0].set(0.0)
        kx, ky, kz = pm.k_list(dtype=jnp.float32)
        k2 = kx * kx + ky * ky + kz * kz
        kk = jnp.sqrt(k2)
        mu = jnp.where(kk == 0, 0.0, kz / jnp.where(kk == 0, 1.0, kk))
        herm = pm.hermitian_weights(dtype=jnp.float32)
        wgt = jnp.broadcast_to(herm, p3.shape).reshape(-1)
        dig_x = jnp.digitize(k2.reshape(-1), x2edges)
        dig_mu = jnp.digitize(jnp.broadcast_to(mu, p3.shape).reshape(-1),
                              muedges_j)
        multi = (dig_x * (Nmu + 2) + dig_mu).astype(jnp.int32)
        Psum = jnp.bincount(multi, weights=p3.reshape(-1) * wgt,
                            length=nbins)
        Nsum = jnp.bincount(multi, weights=wgt, length=nbins)
        return Psum, Nsum

    # compile + warm
    out = fftpower(pos)
    jax.block_until_ready(out)
    # steady state
    reps = 3
    t0 = time.time()
    for _ in range(reps):
        out = fftpower(pos)
    jax.block_until_ready(out)
    return (time.time() - t0) / reps


def main():
    configs = [
        (1024, 100_000_000),
        (1024, 10_000_000),
        (512, 10_000_000),
        (256, 1_000_000),
        (128, 100_000),
    ]
    for Nmesh, Npart in configs:
        # autotune at the config's own scale (capped probe size): the
        # sort kernel's memory/cost profile changes with Nmesh/Npart,
        # so a small-probe winner must not be forced on large configs
        try:
            method = autotune_paint(Nmesh=Nmesh,
                                    Npart=min(Npart, 5_000_000))
        except Exception as e:
            print("autotune failed (%s); using scatter" % str(e)[:120],
                  file=sys.stderr)
            method = 'scatter'
        try:
            dt = run_config(Nmesh, Npart, paint_method=method)
            metric = "fftpower_wallclock_nmesh%d_npart%.0e" % (Nmesh, Npart)
            print(json.dumps({
                "metric": metric,
                "value": round(dt, 4),
                "unit": "s",
                "vs_baseline": round(NOMINAL_BASELINE_S / dt, 2),
            }))
            return 0
        except Exception as e:
            print("config Nmesh=%d Npart=%d failed: %s" % (Nmesh, Npart,
                  str(e)[:200]), file=sys.stderr)
    print(json.dumps({"metric": "fftpower_wallclock", "value": -1,
                      "unit": "s", "vs_baseline": 0}))
    return 1


if __name__ == '__main__':
    sys.exit(main())
