"""Flagship benchmark: FFTPower wall-clock on the north-star config.

Target metric (BASELINE.json): FFTPower wallclock @ Nmesh=1024^3, 1e8
particles. The pipeline measured is the fused jitted program
paint -> rfft -> window compensation -> |delta_k|^2 -> (k, mu) binning —
the same work the reference does across pmesh C paint + pfft MPI FFT +
the project_to_basis slab loop (SURVEY.md §3.1).

Prints ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}

``vs_baseline`` is (estimated reference wallclock) / (ours) — >1 means
faster than the baseline. The reference publishes no absolute numbers
(BASELINE.md); we use a 30 s nominal for the dm_like-scale FFTPower on a
16-rank MPI node (the reference's example production config,
nersc/example-job.slurm), documented here so the denominator is stable
across rounds.

Robustness (round-2 hardening — the round-1 bench burned its whole
window on a wedged axon tunnel):
- the orchestrator process NEVER imports jax; every probe/measurement
  runs in a subprocess with a hard timeout, so a wedged backend init
  cannot consume the window;
- a cheap backend health probe gates everything; if it fails we print a
  JSON line immediately (value -1) instead of timing out silently;
- configs run smallest-first so SOME number always exists, escalating
  to the north-star config; the largest successful config is reported;
- a paint-only microbenchmark is recorded to stderr and
  BENCH_DETAIL.json for kernel-level tracking.

Subcommands (internal):
    bench.py --probe                 backend sanity check
    bench.py --config N NPART [m]    one fftpower config, JSON on stdout
    bench.py --paint N NPART         paint-only microbench
    bench.py --autotune N NPART      pick paint kernel ('sort'|'scatter')
"""

import json
import os
import subprocess
import sys
import time

TOTAL_BUDGET_S = float(os.environ.get('BENCH_BUDGET_S', 1500))
PROBE_TIMEOUT_S = float(os.environ.get('BENCH_PROBE_TIMEOUT_S', 150))
NOMINAL_BASELINE_S = 30.0  # see module docstring


def _setup_jax():
    """Import jax safely under axon: honor an explicit cpu request the
    way __graft_entry__.py does (the sitecustomize overrides
    JAX_PLATFORMS/XLA_FLAGS env vars, so re-assert via jax.config)."""
    import re
    import jax
    if 'cpu' in os.environ.get('JAX_PLATFORMS', ''):
        jax.config.update('jax_platforms', 'cpu')
        m = re.search(r'xla_force_host_platform_device_count=(\d+)',
                      os.environ.get('XLA_FLAGS', ''))
        n = int(m.group(1)) if m else int(
            os.environ.get('JAX_NUM_CPU_DEVICES', '0') or 0)
        if n > 1:
            jax.config.update('jax_num_cpu_devices', n)
    return jax


def cmd_probe():
    jax = _setup_jax()
    import jax.numpy as jnp
    d = jax.devices()
    x = jnp.ones((128, 128))
    s = float((x @ x).sum())
    assert s == 128.0 * 128 * 128
    print(json.dumps({"platform": d[0].platform,
                      "kind": getattr(d[0], 'device_kind', '?'),
                      "n": len(d)}))
    return 0


def _bench_fftpower_fn(pm, Npart, resampler='cic', slab_chunks=16):
    """The fused pipeline with slab-chunked (k,mu) binning.

    Binning loops over chunks of the complex field's leading axis with a
    fori_loop so no full-mesh f32 temporaries (k2/mu/digitize indices)
    are ever live at once — at Nmesh=1024 the unchunked version needs
    ~6 extra 2.1 GB buffers, which does not fit v5e HBM alongside the
    FFT workspace.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    from nbodykit_tpu.ops.window import compensation_transfer
    from nbodykit_tpu.ops.histogram import hist2d_mxu

    Nmesh = int(pm.Nmesh[0])
    L = float(pm.BoxSize[0])
    # kedges at integer multiples of the fundamental 2*pi/L (the
    # reference's dk default): binning runs on INTEGER lattice norms
    # (isq = ix^2+iy^2+iz^2 vs edge m^2), which is exact — float
    # digitize puts on-edge lattice modes (any isq that is a perfect
    # square) on a rounding-dependent side
    Nx = Nmesh // 2
    Nmu = 10
    isq_edges = jnp.asarray((np.arange(Nx + 1, dtype='i8') ** 2)
                            .astype('i4'))
    transfer = compensation_transfer(resampler, False)
    V = L ** 3

    N1c, N0c, nz = pm.shape_complex  # transposed complex layout
    assert N1c % slab_chunks == 0
    rows = N1c // slab_chunks

    # integer lattice coordinates in the transposed layout
    iy_flat = jnp.asarray(np.fft.fftfreq(N1c, d=1.0 / N1c).astype('i4'))
    ix_full = jnp.asarray(np.fft.fftfreq(N0c, d=1.0 / N0c)
                          .astype('i4')).reshape(1, N0c, 1)
    iz_full = jnp.asarray(np.arange(nz, dtype='i4')).reshape(1, 1, nz)

    def fftpower(pos):
        n = pos.shape[0]
        field = pm.paint(pos, 1.0, resampler=resampler)
        field = field / (n / pm.Ntot)
        c = pm.r2c(field)
        w = pm.k_list(dtype=jnp.float32, circular=True)
        c = transfer(w, c)
        p3 = (jnp.abs(c) ** 2).astype(jnp.float32) * V
        p3 = p3.at[0, 0, 0].set(0.0)
        herm_z = pm.hermitian_weights(dtype=jnp.float32)  # (1,1,nz)

        def body(i, acc):
            Psum, Nsum = acc
            sl = jax.lax.dynamic_slice(p3, (i * rows, 0, 0),
                                       (rows, N0c, nz))
            iy = jax.lax.dynamic_slice(iy_flat, (i * rows,),
                                       (rows,)).reshape(rows, 1, 1)
            isq = (ix_full * ix_full + iy * iy + iz_full * iz_full)
            wgt = jnp.broadcast_to(herm_z, sl.shape).reshape(-1)
            dig_k = jnp.searchsorted(
                isq_edges, jnp.broadcast_to(isq, sl.shape).reshape(-1),
                side='right')
            # exact integer mu binning (edges m/5, m=-5..5; mu >= 0 on
            # the half-spectrum): mu >= m/5  <=>  25*iz^2 >= m^2*isq.
            # Float mu is rounding-ambiguous exactly on the Pythagorean
            # lattice ratios (3/5, 4/5, 1) the edges hit.
            izsq25 = 25 * iz_full * iz_full
            dig_mu = sum((izsq25 >= (m * m) * isq).astype(jnp.int32)
                         for m in range(1, Nmu // 2 + 1))
            dig_mu = jnp.where(isq == 0, 0, dig_mu) + (Nmu // 2 + 1)
            dig_mu = jnp.broadcast_to(dig_mu, sl.shape).reshape(-1)
            # MXU one-hot-matmul histogram: ~5x faster than
            # scatter-add bincount on TPU (see ops/histogram.py)
            P_c, N_c = hist2d_mxu(dig_k, dig_mu,
                                  [sl.reshape(-1) * wgt, wgt],
                                  Nx + 2, Nmu + 2,
                                  acc_dtype=jnp.float32)
            return Psum + P_c, Nsum + N_c

        init = (jnp.zeros((Nx + 2, Nmu + 2), jnp.float32),
                jnp.zeros((Nx + 2, Nmu + 2), jnp.float32))
        return jax.lax.fori_loop(0, slab_chunks, body, init)

    return fftpower


def _make_pos(jax, jnp, Npart, L, seed=7):
    pos = jax.random.uniform(jax.random.key(seed), (Npart, 3),
                             jnp.float32, 0.0, L)
    _sync(jax, pos)
    return pos


def _sync(jax, out):
    """Force completion by transferring one scalar to the host.

    ``jax.block_until_ready`` does NOT reliably wait under the axon
    tunnel (async relay) — round-2 measurements with it reported a
    1e7-particle paint at 0.1 ms. A scalar device->host transfer is an
    actual synchronization point.
    """
    import jax.numpy as jnp
    leaf = jax.tree.leaves(out)[0]
    return float(jnp.asarray(leaf).ravel()[0])


def cmd_config(Nmesh, Npart, method='scatter', reps=3):
    jax = _setup_jax()
    import jax.numpy as jnp
    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh

    nbodykit_tpu.set_options(paint_method=method)
    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=1000.0, dtype='f4')
    pos = _make_pos(jax, jnp, Npart, 1000.0)
    fn = jax.jit(_bench_fftpower_fn(pm, Npart))
    t0 = time.time()
    _sync(jax, fn(pos))
    compile_s = time.time() - t0
    t0 = time.time()
    for _ in range(reps):
        out = fn(pos)
        _sync(jax, out)
    dt = (time.time() - t0) / reps
    print(json.dumps({
        "metric": "fftpower_wallclock_nmesh%d_npart%.0e" % (Nmesh, Npart),
        "value": round(dt, 4),
        "unit": "s",
        "vs_baseline": round(NOMINAL_BASELINE_S / dt, 2),
        "compile_s": round(compile_s, 1),
        "paint_method": method,
    }))
    return 0


def cmd_paint(Nmesh, Npart, method='scatter', reps=3):
    """Paint-only microbenchmark (the #1 perf risk, SURVEY §7)."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh

    nbodykit_tpu.set_options(paint_method=method)
    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=1000.0, dtype='f4')
    pos = _make_pos(jax, jnp, Npart, 1000.0)
    fn = jax.jit(lambda p: pm.paint(p, 1.0, resampler='cic'))
    _sync(jax, fn(pos))
    t0 = time.time()
    for _ in range(reps):
        out = fn(pos)
        _sync(jax, out)
    dt = (time.time() - t0) / reps
    print(json.dumps({
        "metric": "paint_wallclock_nmesh%d_npart%.0e_%s"
                  % (Nmesh, Npart, method),
        "value": round(dt, 4), "unit": "s",
        "mpart_per_s": round(Npart / dt / 1e6, 1),
    }))
    return 0


def cmd_autotune(Nmesh, Npart):
    jax = _setup_jax()
    import jax.numpy as jnp
    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh

    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=1000.0, dtype='f4')
    pos = _make_pos(jax, jnp, Npart, 1000.0)
    times = {}
    for method in ['sort', 'scatter']:
        try:
            with nbodykit_tpu.set_options(paint_method=method):
                f = jax.jit(lambda p: pm.paint(p, 1.0, resampler='cic'))
                _sync(jax, f(pos))
                t0 = time.time()
                for _ in range(2):
                    out = f(pos)
                    _sync(jax, out)
                times[method] = (time.time() - t0) / 2
        except Exception as e:
            print("paint method %s failed: %s" % (method, str(e)[:120]),
                  file=sys.stderr)
            times[method] = float('inf')
    best = min(times, key=times.get)
    print(json.dumps({"best": best,
                      "times": {k: (round(v, 4) if v != float('inf')
                                    else None)
                                for k, v in times.items()}}))
    return 0


# ---------------------------------------------------------------------------
# orchestrator (no jax in this process)

def _run_sub(args, timeout):
    """Run a bench.py subcommand; return parsed last-line JSON or None."""
    cmd = [sys.executable, os.path.abspath(__file__)] + args
    t0 = time.time()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout)
    except subprocess.TimeoutExpired:
        print("[bench] %s TIMED OUT after %.0fs" % (args, timeout),
              file=sys.stderr)
        return None
    dt = time.time() - t0
    if r.stderr.strip():
        tail = r.stderr.strip().splitlines()[-8:]
        print("[bench] %s stderr tail: %s" % (args[0], " | ".join(tail)),
              file=sys.stderr)
    if r.returncode != 0:
        print("[bench] %s rc=%d (%.0fs)" % (args, r.returncode, dt),
              file=sys.stderr)
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith('{'):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def main():
    deadline = time.time() + TOTAL_BUDGET_S
    detail = {"probe": None, "autotune": None, "paint": [], "configs": []}

    def left():
        return deadline - time.time()

    probe = _run_sub(['--probe'], min(PROBE_TIMEOUT_S, left()))
    detail['probe'] = probe
    if probe is None:
        print(json.dumps({"metric": "fftpower_wallclock", "value": -1,
                          "unit": "s", "vs_baseline": 0,
                          "error": "backend probe failed/timed out"}))
        _dump_detail(detail)
        return 1
    print("[bench] backend: %s" % probe, file=sys.stderr)

    # Paint kernel: 'scatter' — measured (with real scalar-transfer
    # sync) at 256^3/1e6 the sort kernel is ~100x slower on v5e, so
    # autotuning it at scale just burns budget and risks a timeout-kill
    # (which wedges the axon tunnel for every later subprocess). The
    # --autotune subcommand remains for manual kernel comparisons.
    method = 'scatter'

    # paint microbench at a mid scale
    if left() > 240:
        p = _run_sub(['--paint', '512', '10000000', method],
                     min(420, left()))
        detail['paint'].append(p)
        print("[bench] paint micro: %s" % p, file=sys.stderr)

    # smallest-first ladder up to the north-star config; keep the last
    # success.
    ladder = [
        (128, 100_000, 120),
        (256, 1_000_000, 180),
        (512, 10_000_000, 480),
        (1024, 10_000_000, 700),
        (1024, 100_000_000, 700),
    ]
    best = None
    for Nmesh, Npart, budget in ladder:
        if left() < budget * 0.5:
            print("[bench] skipping Nmesh=%d Npart=%d (%.0fs left)"
                  % (Nmesh, Npart, left()), file=sys.stderr)
            break
        res = _run_sub(['--config', str(Nmesh), str(Npart), method],
                       min(budget, left()))
        detail['configs'].append(res)
        if res is None:
            print("[bench] config Nmesh=%d Npart=%d failed; stopping "
                  "escalation" % (Nmesh, Npart), file=sys.stderr)
            break
        best = res
        print("[bench] ok: %s" % res, file=sys.stderr)

    _dump_detail(detail)
    if best is None:
        print(json.dumps({"metric": "fftpower_wallclock", "value": -1,
                          "unit": "s", "vs_baseline": 0,
                          "error": "no config succeeded"}))
        return 1
    out = {k: best[k] for k in ("metric", "value", "unit", "vs_baseline")}
    print(json.dumps(out))
    return 0


def _dump_detail(detail):
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               'BENCH_DETAIL.json'), 'w') as f:
            json.dump(detail, f, indent=1)
    except OSError:
        pass


if __name__ == '__main__':
    argv = sys.argv[1:]
    if not argv:
        sys.exit(main())
    if argv[0] == '--probe':
        sys.exit(cmd_probe())
    if argv[0] == '--config':
        sys.exit(cmd_config(int(argv[1]), int(argv[2]),
                            *(argv[3:4] or ['scatter'])))
    if argv[0] == '--paint':
        sys.exit(cmd_paint(int(argv[1]), int(argv[2]),
                           *(argv[3:4] or ['scatter'])))
    if argv[0] == '--autotune':
        sys.exit(cmd_autotune(int(argv[1]), int(argv[2])))
    print("unknown args: %r" % (argv,), file=sys.stderr)
    sys.exit(2)
