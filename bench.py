"""Flagship benchmark: FFTPower wall-clock on the north-star config.

Target metric (BASELINE.json): FFTPower wallclock @ Nmesh=1024^3, 1e8
particles. The pipeline measured is the fused jitted program
paint -> rfft -> window compensation -> |delta_k|^2 -> (k, mu) binning —
the same work the reference does across pmesh C paint + pfft MPI FFT +
the project_to_basis slab loop (SURVEY.md §3.1).

Prints ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}

``vs_baseline`` is (same-config baseline wallclock) / (ours) — >1 means
faster. The reference publishes no absolute numbers (BASELINE.md) and
its native stack (pmesh/pfft/mpi4py) is not installable here, so the
baseline is the SAME pipeline measured on this host's CPU at the SAME
config (committed per-config in BASELINE_CPU.json, else this run's
forced-CPU worker). A config with no same-config CPU measurement gets
no vs_baseline at all — cross-config ratios are not speedups.

Round-3 redesign (rounds 1+2 produced no number — VERDICT.md weak #1):
the axon TPU tunnel WEDGES when a process with in-flight TPU work is
timeout-killed, and rounds 1+2 both died that way (r01: the bench
itself was killed at budget; r02: the probe subprocess was killed at
150 s and every later subprocess hung). Therefore:

- ONE persistent worker process runs the whole ladder; it is spawned
  detached (its own session) and is NEVER killed, by anyone. If it
  hangs, it is left hanging and the orchestrator reports what was
  already flushed.
- The worker starts with the tiniest possible op and escalates
  Nmesh 128 -> 256 -> 512 -> 1024 smallest-first, so SOME number
  exists as early as possible.
- The worker atomically rewrites BENCH_DETAIL.json after EVERY
  step (write temp + rename) — partial progress survives any failure.
- The orchestrator (no jax in-process) polls BENCH_DETAIL.json until
  the worker finishes or the budget elapses, then prints the largest
  successful config. It exits 0 with a value even when the tunnel is
  wedged (value -1 + diagnosis), never leaving an empty artifact.
- Per-config phase breakdown (paint / FFT / binning / fused) plus
  throughput estimates (Mpart/s, effective GB/s) are recorded in
  BENCH_DETAIL.json.

Round-4 hardening: round 3's "measurement" was silently a CPU fallback
(the tunnel was wedged at bench time and the worker's backend came up
as platform='cpu'). Now every record carries its platform; a CPU
fallback runs a reduced ladder and is never headlined as a TPU number;
and every real-TPU config measured at ANY point during the round is
merged into the committed BENCH_TPU_CACHE.json, which the orchestrator
falls back to when the live run cannot reach the TPU.

Subcommands (internal):
    bench.py --worker                 run the full ladder (imports jax)
    bench.py --config N NPART [m]     one fftpower config, JSON on stdout
    bench.py --paint N NPART          paint-only microbench
    bench.py --fft-decomp-compare N [reps]
                                      slab-vs-pencil distributed rFFT
                                      on the multi-device mesh
    bench.py --ingest [NPART [NMESH [CHUNK_ROWS [SEED]]]]
                                      streaming catalog ingestion GB/s
                                      (cold / cache-hit / serialized)
                                      + e2e data_ref serving
    bench.py --integrity [NMESH [NPART [REPS [SEED]]]]
                                      tier-0 guard overhead (off vs
                                      cheap) + the detect/retry proof
                                      under an NBKIT_FAULTS corrupt
                                      rule (docs/INTEGRITY.md)

Global flags (any subcommand): --fft-decomp {slab,pencil,auto} and
--pencil PXxPY override the FFT decomposition for the run; the
record's tuned:{...} stamps what actually resolved.
"""

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
DETAIL_PATH = os.environ.get(
    'BENCH_DETAIL_PATH', os.path.join(HERE, 'BENCH_DETAIL.json'))
CPU_DETAIL_PATH = os.path.join(HERE, 'BENCH_DETAIL_CPU.json')
WORKER_LOG = os.environ.get(
    'BENCH_WORKER_LOG', os.path.join(HERE, 'BENCH_WORKER.log'))
# Committed cache of the best REAL-TPU measurements ever taken: the
# round-3 "result" was silently a CPU fallback (BENCH_DETAIL.json
# probe.platform == 'cpu') because the tunnel was wedged at bench time.
# Any TPU config measured at any point during a round lands here, so
# the end-of-round bench can report it even if the tunnel is down then.
TPU_CACHE_PATH = os.path.join(HERE, 'BENCH_TPU_CACHE.json')
TOTAL_BUDGET_S = float(os.environ.get('BENCH_BUDGET_S', 1500))
# in-progress measurements staged here (atomic) BEFORE the final
# timing barrier, so a tunnel death mid-timing leaves the partial
# number on disk (round 5 lost the 1024^3/1e7 record exactly there)
STAGED_PATH = os.environ.get('BENCH_STAGED_PATH',
                             os.path.join(HERE, 'BENCH_STAGED.json'))
# crash-safe span trace of every worker phase (nbodykit_tpu.
# diagnostics, docs/OBSERVABILITY.md); set BENCH_TRACE_DIR='' to
# disable
TRACE_DIR = os.environ.get('BENCH_TRACE_DIR',
                           os.path.join(HERE, 'BENCH_TRACE'))
# per-rep checkpoints (nbodykit_tpu.resilience, docs/RESILIENCE.md):
# a SIGKILLed / tunnel-killed run resumes its timed reps on relaunch
# instead of restarting, and the record carries resumed: true
CKPT_DIR = os.environ.get('BENCH_CKPT_DIR',
                          os.path.join(HERE, 'BENCH_CKPT'))

TPU_PLATFORMS = ('tpu', 'axon')

# v5e single-chip nominals for efficiency estimates
V5E_HBM_GBPS = 819.0

# global FFT decomposition overrides (--fft-decomp / --pencil), staged
# here by _parse_fft_flags and applied by _setup_jax once jax is up;
# every record's tuned:{...} then stamps the decomposition and device-
# mesh shape the measurement actually ran with (tuned_snapshot)
_FFT_OPTS = {}


def _parse_fft_flags(argv):
    """Strip the global ``--fft-decomp slab|pencil|auto``,
    ``--pencil PXxPY``, ``--mesh-dtype f4|bf16`` and
    ``--a2a-compress none|bf16|int16`` flags from an argv list (any
    subcommand may carry them) and stage the overrides for
    :func:`_setup_jax`.  The precision flags select the ISSUE 13
    half-storage/compressed-wire paths; every record's ``tuned:{...}``
    block stamps the resolved values so hardware-window numbers stay
    attributable."""
    out = []
    it = iter(argv)
    for a in it:
        if a == '--fft-decomp':
            _FFT_OPTS['fft_decomp'] = next(it)
        elif a.startswith('--fft-decomp='):
            _FFT_OPTS['fft_decomp'] = a.split('=', 1)[1]
        elif a == '--pencil':
            _FFT_OPTS['fft_pencil'] = next(it)
        elif a.startswith('--pencil='):
            _FFT_OPTS['fft_pencil'] = a.split('=', 1)[1]
        elif a == '--mesh-dtype':
            _FFT_OPTS['mesh_dtype'] = next(it)
        elif a.startswith('--mesh-dtype='):
            _FFT_OPTS['mesh_dtype'] = a.split('=', 1)[1]
        elif a == '--a2a-compress':
            _FFT_OPTS['a2a_compress'] = next(it)
        elif a.startswith('--a2a-compress='):
            _FFT_OPTS['a2a_compress'] = a.split('=', 1)[1]
        else:
            out.append(a)
    if _FFT_OPTS.get('fft_decomp') not in (None, 'slab', 'pencil',
                                           'auto'):
        raise SystemExit('--fft-decomp must be slab, pencil or auto '
                         '(got %r)' % _FFT_OPTS['fft_decomp'])
    if _FFT_OPTS.get('mesh_dtype') not in (None, 'f4', 'bf16', 'auto'):
        raise SystemExit('--mesh-dtype must be f4, bf16 or auto '
                         '(got %r)' % _FFT_OPTS['mesh_dtype'])
    if _FFT_OPTS.get('a2a_compress') not in (None, 'none', 'bf16',
                                             'int16', 'auto'):
        raise SystemExit('--a2a-compress must be none, bf16, int16 or '
                         'auto (got %r)' % _FFT_OPTS['a2a_compress'])
    return out


def _bench_mesh_dtype(Nmesh=None):
    """The mesh storage dtype this bench process runs with: the
    ``--mesh-dtype`` override when given (staged into
    ``set_options(mesh_dtype=...)`` by :func:`_setup_jax`), resolved
    through the tune cache for 'auto', else 'f4'."""
    from nbodykit_tpu import _global_options
    v = _global_options['mesh_dtype']
    if v in (None, 'auto'):
        from nbodykit_tpu.tune.resolve import resolve_mesh_dtype
        return resolve_mesh_dtype(nmesh=Nmesh)
    return v


def _utcnow():
    return time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())


def _stamp(rec):
    """Every emitted record carries the measurement's REAL timestamp:
    the regression tracker (nbodykit_tpu.diagnostics.regress) judges
    evidence freshness from it, so a replayed number can never pass as
    a fresh one just because it was printed today."""
    rec.setdefault('measured_at', _utcnow())
    return rec


def _setup_jax():
    """Import jax, honoring an explicit cpu request the way
    __graft_entry__.py does (the sitecustomize overrides JAX_PLATFORMS/
    XLA_FLAGS env vars, so re-assert via jax.config)."""
    import re
    import jax
    if 'cpu' in os.environ.get('JAX_PLATFORMS', ''):
        jax.config.update('jax_platforms', 'cpu')
        m = re.search(r'xla_force_host_platform_device_count=(\d+)',
                      os.environ.get('XLA_FLAGS', ''))
        n = int(m.group(1)) if m else int(
            os.environ.get('JAX_NUM_CPU_DEVICES', '0') or 0)
        if n > 1:
            from nbodykit_tpu._jax_compat import set_cpu_devices
            set_cpu_devices(n)
    # persistent compile cache: the ladder re-jits the same programs
    # (and a re-run after a tunnel wedge should not pay compiles again);
    # same dir + env override as __graft_entry__._enable_compile_cache
    # so the dryrun/bench/test caches stay shared
    import __graft_entry__
    __graft_entry__._enable_compile_cache()
    if TRACE_DIR:
        # every worker phase below emits crash-safe spans: a wedged
        # tunnel or a kill leaves BENCH_TRACE/trace-<pid>.jsonl
        # readable (python -m nbodykit_tpu.diagnostics --report ...)
        import nbodykit_tpu
        nbodykit_tpu.set_options(diagnostics=TRACE_DIR)
    if _FFT_OPTS:
        import nbodykit_tpu
        nbodykit_tpu.set_options(**_FFT_OPTS)
    return jax


def _sync(jax, out):
    """Force completion by transferring one scalar to the host.

    ``jax.block_until_ready`` does NOT reliably wait under the axon
    tunnel (async relay) — round-2 measurements with it reported a
    1e7-particle paint at 0.1 ms. A scalar device->host transfer is an
    actual synchronization point.
    """
    import jax.numpy as jnp
    leaf = jnp.asarray(jax.tree.leaves(out)[0]).ravel()[0]
    if jnp.iscomplexobj(leaf):
        # axon implements no complex host transfers; reduce on device
        leaf = jnp.abs(leaf)
    return float(leaf)


def _make_pos(jax, jnp, Npart, L, seed=7):
    pos = jax.random.uniform(jax.random.key(seed), (Npart, 3),
                             jnp.float32, 0.0, L)
    _sync(jax, pos)
    return pos


def _bench_fftpower_fn(pm, resampler='cic', slab_chunks=16):
    """The fused pipeline with slab-chunked (k,mu) binning.

    Binning loops over chunks of the complex field's leading axis with
    a fori_loop so no full-mesh f32 temporaries (k2/mu/digitize
    indices) are ever live at once — at Nmesh=1024 the unchunked
    version needs ~6 extra 2.1 GB buffers, which does not fit v5e HBM
    alongside the FFT workspace.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    from nbodykit_tpu.ops.window import compensation_transfer
    from nbodykit_tpu.ops.histogram import (hist2d_weighted,
                                            lattice_shell_index)

    Nmesh = int(pm.Nmesh[0])
    L = float(pm.BoxSize[0])
    # kedges at integer multiples of the fundamental 2*pi/L (the
    # reference's dk default): binning runs on INTEGER lattice norms
    # (isq = ix^2+iy^2+iz^2 vs edge m^2), which is exact — float
    # digitize puts on-edge lattice modes (any isq that is a perfect
    # square) on a rounding-dependent side
    Nx = Nmesh // 2
    Nmu = 10
    transfer = compensation_transfer(resampler, False)
    V = L ** 3

    N1c, N0c, nz = pm.shape_complex  # transposed complex layout
    assert N1c % slab_chunks == 0
    rows = N1c // slab_chunks

    # integer lattice coordinates in the transposed layout
    iy_flat = jnp.asarray(np.fft.fftfreq(N1c, d=1.0 / N1c).astype('i4'))
    ix_full = jnp.asarray(np.fft.fftfreq(N0c, d=1.0 / N0c)
                          .astype('i4')).reshape(1, N0c, 1)
    iz_full = jnp.asarray(np.arange(nz, dtype='i4')).reshape(1, 1, nz)

    def binning(p3):
        herm_z = pm.hermitian_weights(dtype=jnp.float32)  # (1,1,nz)

        def body(i, acc):
            Psum, Nsum = acc
            sl = jax.lax.dynamic_slice(p3, (i * rows, 0, 0),
                                       (rows, N0c, nz))
            iy = jax.lax.dynamic_slice(iy_flat, (i * rows,),
                                       (rows,)).reshape(rows, 1, 1)
            isq = (ix_full * ix_full + iy * iy + iz_full * iz_full)
            wgt = jnp.broadcast_to(herm_z, sl.shape).reshape(-1)
            # k-bin = floor(sqrt(isq)) + 1 (shell Nx is the overflow
            # bin): exact shell assignment via the shared helper
            dig_k = lattice_shell_index(isq, Nx + 1) + 1
            dig_k = jnp.broadcast_to(dig_k, sl.shape).reshape(-1)
            # exact integer mu binning (edges m/5, m=-5..5; mu >= 0 on
            # the half-spectrum): mu >= m/5  <=>  25*iz^2 >= m^2*isq.
            # Float mu is rounding-ambiguous exactly on the Pythagorean
            # lattice ratios (3/5, 4/5, 1) the edges hit.
            izsq25 = 25 * iz_full * iz_full
            # bounded: m^2*isq <= 25 * 3*(Nmesh/2)^2 = 3.1e8 even at
            # Nmesh=4096 — far below 2^31, so i32 is safe by
            # construction  # nbkl: disable=NBK302,NBK704
            dig_mu = sum((izsq25 >= (m * m) * isq).astype(jnp.int32)
                         for m in range(1, Nmu // 2 + 1))
            dig_mu = jnp.where(isq == 0, 0, dig_mu) + (Nmu // 2 + 1)
            dig_mu = jnp.broadcast_to(dig_mu, sl.shape).reshape(-1)
            # MXU one-hot-matmul histogram on TPU, scatter-add
            # bincount elsewhere (the MXU path emulated on CPU is
            # ~100x slower — the round-3 CPU-fallback trap)
            P_c, N_c = hist2d_weighted(dig_k, dig_mu,
                                       [sl.reshape(-1) * wgt, wgt],
                                       Nx + 2, Nmu + 2,
                                       acc_dtype=jnp.float32)
            return Psum + P_c, Nsum + N_c

        init = (jnp.zeros((Nx + 2, Nmu + 2), jnp.float32),
                jnp.zeros((Nx + 2, Nmu + 2), jnp.float32))
        return jax.lax.fori_loop(0, slab_chunks, body, init)

    def comp_pow(c):
        w = pm.k_list(dtype=jnp.float32, circular=True)
        c = transfer(w, c)
        p3 = (jnp.abs(c) ** 2).astype(jnp.float32) * V
        return p3.at[0, 0, 0].set(0.0)

    def field_power(field):
        return comp_pow(pm.r2c(field))

    def paint(pos):
        # return_dropped satisfies the traced-mxu overflow contract;
        # run_config checks the count once per config via
        # 'paint_dropped' (uniform bench data cannot overflow the
        # default slack, but the check keeps the number honest)
        field, _ = pm.paint(pos, 1.0, resampler=resampler,
                            return_dropped=True)
        return field

    def power3d(pos):
        n = pos.shape[0]
        return field_power(paint(pos) / (n / pm.Ntot))

    def fftpower(pos):
        return binning(power3d(pos))

    phases = {
        'paint': paint,
        'paint_dropped': lambda pos: pm.paint(
            pos, 1.0, resampler=resampler, return_dropped=True)[1],
        'paint_fft': lambda pos: pm.r2c(paint(pos)),
        'power3d': power3d,
        # staged-pipeline pieces: at Nmesh>=512 the axon remote-compile
        # helper dies (HTTP 500) on the single fused program, while the
        # stages compile fine individually; run_config falls back to
        # paint -> field_power -> binning as three jits (intermediates
        # stay on device; one extra HBM roundtrip of the field)
        'field_power': field_power,
        'comp_pow': comp_pow,
        'binning': binning,
    }
    return fftpower, phases


def _timed_reps(once, reps, label, ckpt=None, key=None, rec=None,
                ladder=None):
    """The timed measurement queue, run under the resilience stack
    (nbodykit_tpu.resilience, docs/RESILIENCE.md):

    - each rep runs under a :class:`Supervisor` — injected or real
      ``UNAVAILABLE``/deadline faults get bounded-backoff retries, and
      ``RESOURCE_EXHAUSTED`` steps down the FFT/paint memory ladder
      when one is passed (only paths that re-read the options per call
      — the eager lowmem FFT drivers, convpower's eager compose — can
      profit; a compiled fused program cannot, its OOM falls through
      to run_config's structural staged fallback);
    - each completed rep commits an atomic checkpoint, so a run killed
      mid-timing resumes at the next rep on relaunch and the final
      record carries ``resumed: true`` (round 5 lost the 1024³ record
      exactly there);
    - ``bench.rep`` is a named fault point: ``NBKIT_FAULTS=
      'bench.rep@2:kill'`` rehearses the mid-rep death on CPU.

    ``once`` must run AND sync one rep.  Returns the mean rep wall.
    """
    from nbodykit_tpu.diagnostics import span
    from nbodykit_tpu.resilience import (Supervisor, check_preemption,
                                         fault_point)
    sup = Supervisor('bench.%s' % label, ladder=ladder, checkpoint=ckpt)
    done, elapsed = 0, 0.0
    if ckpt is not None and key is not None:
        got = sup.resume(key, validate=lambda s: (
            s.get('reps') == reps and s.get('label') == label
            and 0 < s.get('completed', 0) <= reps))
        if got is not None:
            done = int(got[0]['completed'])
            elapsed = float(got[0].get('elapsed_s', 0.0))
            if rec is not None:
                rec['resumed'] = True
                rec['resumed_reps'] = done
    completed = done
    try:
        for r in range(done, reps):
            fault_point('bench.rep')
            # the rep boundary is the safe point: every completed rep is
            # already checkpointed, so a SIGTERM'd run stops HERE (zero
            # recomputed reps on relaunch) instead of starting rep r
            check_preemption('bench.%s.rep%d' % (label, r))
            t0 = time.time()
            with span('bench.rep', label=label, rep=r):
                sup.run(once)
            elapsed += time.time() - t0
            completed = r + 1
            if key is not None:
                sup.save(key, {'label': label, 'reps': reps,
                               'completed': completed,
                               'elapsed_s': round(elapsed, 6)})
    except Exception:
        from nbodykit_tpu.resilience import preemption_requested
        if preemption_requested() and rec is not None:
            # the per-rep checkpoint above is the sealed state; the
            # staged record marks the rung interrupted-but-resumable
            rec['preempted'] = True
            _stage_partial(rec, partial=True, stage='preempted',
                           completed_reps=completed)
        raise
    if rec is not None and sup.events:
        retr = [e for e in sup.events if e['kind'] == 'retries']
        degr = [e for e in sup.events if e['kind'] == 'degradations']
        if retr:
            rec['retries'] = len(retr)
        if degr:
            rec['degradations'] = [
                dict(e.get('detail', {}), rung=e.get('rung'))
                for e in degr]
    return elapsed / reps


def _time_fn(jax, fn, args, reps, label='fn', on_warm=None, ckpt=None,
             key=None, rec=None):
    """Warm (compile) + timed reps.  ``on_warm(compile_s)`` fires after
    the warm-up sync and BEFORE the timed loop — the hook run_config
    uses to stage a partial record ahead of the final timing barrier
    (a tunnel death mid-reps then still leaves a number on disk).
    The reps themselves run checkpointed + supervised
    (:func:`_timed_reps`)."""
    from nbodykit_tpu.diagnostics import span
    with span('bench.warmup', label=label):
        out = fn(*args)
        t0 = time.time()
        _sync(jax, out)
        compile_s = time.time() - t0  # first-call includes compile
    if on_warm is not None:
        on_warm(compile_s)
    dt = _timed_reps(lambda: _sync(jax, fn(*args)), reps, label,
                     ckpt=ckpt, key=key, rec=rec)
    return dt, compile_s


def _baseline_for(metric):
    """Same-config CPU baseline for ``vs_baseline``, or None.

    vs_baseline is only ever a SAME-CONFIG ratio: the measured CPU
    wallclock of the identical pipeline/config on this host (the
    reference implementation itself is not runnable here — its native
    stack pmesh/pfft/mpi4py is not installed and installs are
    unavailable — so our pipeline on CPU is the stated stand-in,
    labeled as such). Sources, in preference order: the committed
    per-config store BASELINE_CPU.json, then this run's forced-CPU
    worker detail. A config with no same-config CPU measurement gets NO
    vs_baseline — a 256-cubed timing divided by a 1024-cubed nominal is
    not a speedup (round-4 verdict, Weak #1).
    """
    for path, src in ((os.path.join(HERE, 'BASELINE_CPU.json'),
                       'BASELINE_CPU.json'),
                      (CPU_DETAIL_PATH, 'cpu worker (this run)')):
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        recs = data.get('results', {}).values() if 'results' in data \
            else data.get('configs', [])
        for rec in recs:
            if (rec and rec.get('metric') == metric
                    and rec.get('platform') == 'cpu'
                    and rec.get('value', -1) > 0):
                return float(rec['value']), src
    return None


def _attach_baseline(rec):
    # purge any pre-existing ratio first: cached records from earlier
    # rounds carry the old cross-config nominal-based vs_baseline,
    # which must never be republished when no same-config baseline
    # exists (round-4 verdict, Weak #1)
    for k in ('vs_baseline', 'baseline_s', 'baseline_source'):
        rec.pop(k, None)
    base = _baseline_for(rec.get('metric'))
    if base is not None and rec.get('value', -1) > 0:
        rec['vs_baseline'] = round(base[0] / rec['value'], 2)
        rec['baseline_s'] = base[0]
        rec['baseline_source'] = 'same-config CPU pipeline, ' + base[1]
    return rec


def run_config(Nmesh, Npart, method='scatter', reps=2, phases=True):
    """One full config measurement; returns a result dict."""
    jax = _setup_jax()
    overridden = False
    if Npart >= 50_000_000 and method == 'sort' \
            and jax.devices()[0].platform in TPU_PLATFORMS:
        # sort paint materializes ~16 bytes * 8 * Npart of sort
        # temporaries (~13 GB at 1e8) — over v5e HBM next to the
        # field; the chunked scatter paint bounds its live set
        method, overridden = 'scatter', True
    import jax.numpy as jnp
    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh

    # reset the engine options too: a prior suffixed run_paint in this
    # process must not leak non-default engines into a rung labeled
    # only by paint_method
    nbodykit_tpu.set_options(paint_method=method, paint_order='auto',
                             paint_deposit='auto', paint_streams='auto',
                             paint_chunk_size=1024 * 1024 * 16)
    from nbodykit_tpu.diagnostics import span as _span
    from nbodykit_tpu.diagnostics import instrumented_jit as _ijit
    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=1000.0,
                      dtype=_bench_mesh_dtype(Nmesh))
    with _span('bench.make_pos', npart=Npart, nmesh=Nmesh):
        pos = _make_pos(jax, jnp, Npart, 1000.0)
    fused, phase_fns = _bench_fftpower_fn(pm)

    rec = {
        "metric": "fftpower_wallclock_nmesh%d_npart%.0e" % (Nmesh, Npart),
        "unit": "s", "paint_method": method,
        "platform": jax.devices()[0].platform,
        "nmesh": Nmesh, "npart": Npart,
        **({"paint_method_overridden": "sort->scatter (HBM)"}
           if overridden else {}),
    }
    # which tuned configuration this measurement actually ran with
    # (explicit/default/cache per knob) — a bench number without its
    # config is not reproducible evidence (nbodykit_tpu.tune)
    from nbodykit_tpu.tune.resolve import tuned_snapshot
    rec['tuned'] = tuned_snapshot(nmesh=Nmesh, npart=Npart,
                                  dtype='f4', nproc=pm.nproc)
    # per-rep checkpoints keyed by metric (the TPU + forced-CPU worker
    # pair never collide); a relaunch after a mid-rep death resumes
    # here instead of restarting the rung
    from nbodykit_tpu.resilience import CheckpointStore, default_ladder
    ckpt = CheckpointStore(CKPT_DIR)
    ckpt.gc_tmp()   # sweep stale .tmp debris from earlier killed runs
    ckey = 'bench.' + rec['metric']
    # the axon remote-compile helper dies on the fused program at
    # Nmesh>=512 (HTTP 500 / subprocess exit 1, and the dead helper
    # then hangs every later compile RPC for ~27 min before
    # UNAVAILABLE) — go staged directly there; the three stages
    # compile fine separately and the intermediates never leave the
    # device. Round-5: the FUSED mxu program wedged the tunnel at
    # Nmesh=256 too (the paint-only mxu program had compiled fine
    # moments earlier), so any mxu rung is staged as well.
    staged = (rec['platform'] in TPU_PLATFORMS
              and (Nmesh >= 512 or method == 'mxu'))
    if not staged:
        try:
            dt, compile_s = _time_fn(
                jax, _ijit(fused, label='bench.fused'), (pos,), reps,
                label='fused',
                on_warm=lambda cs: _stage_partial(
                    rec, partial=True, stage='warmed', mode='fused',
                    first_run_s=round(cs, 4)),
                ckpt=ckpt, key=ckey, rec=rec)
            rec['mode'] = 'fused'
        except Exception as e:
            if not any(s in str(e) for s in
                       ('remote_compile', 'RESOURCE', 'UNAVAILABLE',
                        'INTERNAL')):
                raise
            # substring classification can misfire on unrelated errors
            # whose text happens to contain e.g. 'INTERNAL'; keep the
            # trigger visible in the record (round-4 advisor)
            rec['fused_error'] = str(e)[:300]
            staged = True
    if staged:
        rec['mode'] = 'staged'
        s_paint = _ijit(lambda p: phase_fns['paint'](p)
                        / (Npart / pm.Ntot), label='bench.paint')
        # donate every inter-stage buffer: at Nmesh=1024 the real field
        # is ~4.3 GB and the staged peak is workspace-bound (see
        # pmesh.memory_plan) — reusing the input buffers is the
        # difference between fitting v5e HBM and OOM. At >=1024 the
        # combined r2c+|c|^2 program peaks over HBM even with donation
        # (field + two c64 mesh buffers + p3 live in one program), so
        # the FFT and the compensate+|c|^2 run as separate donated jits
        # — each then holds at most ~3 full-mesh buffers.
        s_bin = _ijit(phase_fns['binning'], label='bench.binning',
                      donate_argnums=0)
        if Nmesh >= 1024:
            # the in-jit chunked FFT double-buffers its loop carries
            # (~4 full-mesh buffers — over HBM next to the particles),
            # so the FFT runs as the EAGER Python-chunked driver whose
            # per-chunk donation is aliased in place: ~2 full-mesh
            # buffers peak. The field is handed over in a one-element
            # list so its buffer frees after the first FFT pass.
            from nbodykit_tpu.parallel import dfft as _dfft
            # the lowmem driver bypasses pm.r2c, so its forward
            # normalization (pmesh convention, pmesh.py::r2c) is
            # applied here before the shared power tail
            s_cpow = _ijit(
                lambda c: phase_fns['comp_pow'](c * (1.0 / pm.Ntot)),
                label='bench.comp_pow', donate_argnums=0)

            def paint_fft():
                # the one-element box is built HERE so no caller stack
                # slot references the 4.3 GB field during the FFT call
                # (pre-3.11 CPython keeps argument stack refs alive for
                # the whole call) — the lowmem driver empties the box
                # and frees the field after its first pass
                box = [s_paint(pos)]
                return _dfft.rfftn_single_lowmem(box)

            def run_once():
                return s_bin(s_cpow(paint_fft()))
        else:
            s_power = _ijit(phase_fns['field_power'],
                            label='bench.field_power', donate_argnums=0)
            run_once = lambda: s_bin(s_power(s_paint(pos)))
        with _span('bench.warmup', label='staged'):
            t0 = time.time()
            _sync(jax, run_once())
            compile_s = time.time() - t0
        # the warmed partial record lands on disk BEFORE the timed
        # reps — a tunnel death mid-timing no longer loses the rung
        _stage_partial(rec, partial=True, stage='warmed', mode='staged',
                       first_run_s=round(compile_s, 4))
        # the staged/eager paths re-read the options per call, so the
        # supervisor's OOM ladder (fft_chunk_bytes / paint_chunk_size
        # halving) actually changes the re-run program
        dt = _timed_reps(lambda: _sync(jax, run_once()), reps,
                         'staged', ckpt=ckpt, key=ckey, rec=rec,
                         ladder=default_ladder())
    rec.update(value=round(dt, 4), compile_s=round(compile_s, 1))
    _stamp(rec)
    _stage_partial(rec, partial=False, stage='complete')
    ckpt.delete(ckey)   # the rung is on disk complete; nothing to resume
    _attach_baseline(rec)

    if method == 'mxu':
        rec['paint_dropped'] = int(
            jax.jit(phase_fns['paint_dropped'])(pos))
        if rec['paint_dropped']:
            rec['error'] = ('mxu bucket overflow dropped %d particles '
                            'at default slack' % rec['paint_dropped'])
    def _phase_split():
        field_bytes = 4.0 * Nmesh ** 3
        t_paint, _ = _time_fn(jax, jax.jit(phase_fns['paint']),
                              (pos,), reps)
        if rec['mode'] == 'fused':
            t_pfft, _ = _time_fn(jax, jax.jit(phase_fns['paint_fft']),
                                 (pos,), reps)
            t_p3, _ = _time_fn(jax, jax.jit(phase_fns['power3d']),
                               (pos,), reps)
            t_fft = max(t_pfft - t_paint, 0.0)
            t_bin = max(dt - t_p3, 0.0)
        elif Nmesh >= 1024:
            # prefix-chain timing with the SAME donated stage programs
            # as the measured run (a non-donated variant would hold two
            # extra full-mesh buffers and OOM); sync + del before the
            # next rep so at most one chain's buffers are ever live
            def _time_seq(chain):
                t0 = time.time()
                for _ in range(reps):
                    out = chain()
                    _sync(jax, out)
                    del out
                return (time.time() - t0) / reps

            t_pf = _time_seq(paint_fft)
            t_pfc = _time_seq(lambda: s_cpow(paint_fft()))
            t_fft = max(t_pf - t_paint, 0.0)
            t_bin = max(dt - t_pfc, 0.0)
            rec['phases_note'] = ('fft/comp/bin by donated prefix-chain '
                                  'differences; comp_s=%.4f'
                                  % max(t_pfc - t_pf, 0.0))
        else:
            field = jax.jit(phase_fns['paint'])(pos)
            fp = jax.jit(phase_fns['field_power'])
            t_fp, _ = _time_fn(jax, fp, (field,), reps)
            # materialize the binning input LAST, through the measured
            # run's DONATED program (s_power, compiled already), and
            # drop the stage-buffer name in the same breath: the
            # donation aliases the painted field in place instead of
            # holding it live next to p3 and the binning programs for
            # the whole timed loop (NBK501/NBK502 — one avoidable
            # stage buffer at every staged size)
            p3 = s_power(field)
            del field
            t_bin, _ = _time_fn(jax, jax.jit(phase_fns['binning']),
                                (p3,), reps)
            t_fft = None  # staged stage mixes FFT with transfer/|c|^2;
            # no isolated FFT time, so no bandwidth estimate
        rec['phases'] = {
            'paint_s': round(t_paint, 4),
            'binning_s': round(t_bin, 4),
            'paint_mpart_per_s': round(Npart / t_paint / 1e6, 1),
        }
        if t_fft is not None:
            rec['phases'].update({
                'fft_s': round(t_fft, 4),
                # rfft of N^3 reads+writes the field ~6x across the
                # three axis passes (transposed layout): a rough
                # effective-BW yardstick vs the 819 GB/s v5e HBM nominal
                'fft_eff_gbps': round(6 * field_bytes
                                      / max(t_fft, 1e-9) / 1e9, 1),
                'fft_frac_hbm_peak': round(
                    6 * field_bytes / max(t_fft, 1e-9) / 1e9
                    / V5E_HBM_GBPS, 3),
            })
        else:
            rec['phases']['fftpow_s'] = round(t_fp, 4)

    if phases:
        # the core measurement exists at this point — flush it so a
        # tunnel death during the OPTIONAL phase split cannot lose the
        # rung (it did once: round 5, 1024^3 first landing)
        _cache_tpu_result(rec)
        _cache_cpu_baseline(rec)
        print("[config] core record: %s" % json.dumps(rec), flush=True)
        try:
            with _span('bench.phase_split', nmesh=Nmesh):
                _phase_split()
        except Exception as e:
            rec['phases_error'] = str(e)[:300]
        # refresh the cached records with the phase data (equal-value
        # records are replaced, not kept)
        _cache_tpu_result(rec)
        _cache_cpu_baseline(rec)
    return rec


def run_fkp(Nmesh=512, nbar=1e-4, reps=1):
    """ConvolvedFFTPower (survey path) wallclock — acceptance config #5
    at reduced scale (BASELINE.md; reference
    benchmarks/test_convpower.py: poles=[0,2,4], randoms alpha=10).

    Staged per multipole internally (the Ylm FFT loop is already a
    sequence of separate programs), so no >=512 fused compile reaches
    the axon helper. When a same-config CPU record exists in
    BASELINE_CPU.json, the leading P0 values are compared and the
    relative error recorded as ``p0_vs_cpu_relerr``.
    """
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np
    from nbodykit_tpu.source.catalog.uniform import UniformCatalog
    from nbodykit_tpu.algorithms.convpower import (FKPCatalog,
                                                   ConvolvedFFTPower)

    box = 2500.0
    data = UniformCatalog(nbar=nbar, BoxSize=box, seed=42)
    rand = UniformCatalog(nbar=10 * nbar, BoxSize=box, seed=43)
    data['NZ'] = nbar * jnp.ones(data.size)
    rand['NZ'] = nbar * jnp.ones(rand.size)
    fkp = FKPCatalog(data, rand)
    mesh = fkp.to_mesh(Nmesh=Nmesh, resampler='tsc')

    from nbodykit_tpu.diagnostics import span as _span

    def once():
        with _span('bench.fkp_rep', nmesh=Nmesh):
            cp = ConvolvedFFTPower(mesh, poles=[0, 2, 4], dk=0.005)
            # touching the result forces completion (poles are host
            # arrays)
            float(np.asarray(cp.poles['power_0'].real)[0])
            return cp

    # supervised: round 5's FKP hardware proof died RESOURCE_EXHAUSTED
    # with no response — now an OOM steps down the FFT/paint memory
    # ladder and re-runs (ConvolvedFFTPower composes eagerly, so the
    # degraded options take effect on the very next attempt), and
    # UNAVAILABLE gets bounded-backoff retries
    from nbodykit_tpu.resilience import Supervisor, default_ladder
    sup = Supervisor('bench.fkp', ladder=default_ladder())

    # warm (compiles included in first run)
    t0 = time.time()
    cp = sup.run(once)
    compile_s = time.time() - t0
    t0 = time.time()
    for _ in range(reps):
        cp = sup.run(once)
    dt = (time.time() - t0) / reps

    p0 = np.asarray(cp.poles['power_0'].real)
    rec = {
        "metric": "convpower_wallclock_nmesh%d" % Nmesh,
        "value": round(dt, 4), "unit": "s",
        "compile_s": round(compile_s, 1),
        "platform": jax.devices()[0].platform,
        "nmesh": Nmesh, "npart": int(data.size + rand.size),
        "poles": [0, 2, 4],
        "p0_first5": [float(x) for x in p0[:5]],
        "shotnoise": float(cp.attrs.get('shotnoise', float('nan'))),
    }
    if sup.events:
        degr = [e for e in sup.events if e['kind'] == 'degradations']
        retr = [e for e in sup.events if e['kind'] == 'retries']
        if degr:
            rec['degradations'] = [
                dict(e.get('detail', {}), rung=e.get('rung'))
                for e in degr]
        if retr:
            rec['retries'] = len(retr)
    base = _baseline_for(rec['metric'])
    if base is not None:
        # same-seed catalogs -> the CPU record's P0 must agree
        try:
            with open(os.path.join(HERE, 'BASELINE_CPU.json')) as f:
                cpu_rec = json.load(f)['results'][rec['metric']]
            ref = np.asarray(cpu_rec['p0_first5'])
            got = np.asarray(rec['p0_first5'])
            rec['p0_vs_cpu_relerr'] = float(
                np.max(np.abs(got - ref) / np.maximum(np.abs(ref), 1e-30)))
        except (OSError, KeyError, ValueError):
            pass
    return _stamp(rec)


def run_prim(n=10_000_000, reps=3):
    """Per-element costs of the irregular primitives every paint
    strategy is built from — measured on the actual backend, because
    the scatter/sort/gather rates decide which kernel wins and none of
    them are predictable from specs (TPU scatter serializes; sort is a
    bitonic network; gather throughput varies with layout).

    Runs under a ladder-equipped Supervisor like run_fkp (round 5's
    --prim died RESOURCE_EXHAUSTED on the chip with no response):
    UNAVAILABLE/deadline get bounded-backoff retries, an OOM steps
    down the FFT/paint memory ladder and re-runs the primitive —
    degrading instead of dying, with the supervisor's activity
    recorded on the emitted record.
    """
    jax = _setup_jax()
    import jax.numpy as jnp
    from nbodykit_tpu.resilience import Supervisor, default_ladder

    sup = Supervisor('bench.prim', ladder=default_ladder())

    key = jax.random.key(11)
    M = 134_217_728  # 512^3
    idx = jax.random.randint(key, (n,), 0, M, jnp.int32)
    perm = jax.random.permutation(key, n).astype(jnp.int32)
    vals = jax.random.uniform(key, (n,), jnp.float32)
    small = jax.random.randint(key, (n,), 0, 4096, jnp.int32)
    _sync(jax, (idx, perm, vals, small))

    out = {}

    def t(name, fn, *args):
        f = jax.jit(fn)

        def attempt():
            _sync(jax, f(*args))                 # compile + warm
            t0 = time.time()
            for _ in range(reps):
                _sync(jax, f(*args))
            return (time.time() - t0) / reps

        try:
            dt = sup.run(attempt)
            out[name] = {"s": round(dt, 4),
                         "ns_per_elt": round(dt / n * 1e9, 2)}
        except Exception as e:
            # the primitive is infeasible even degraded; record and
            # move on — one dead primitive must not kill the sweep
            out[name] = {"error": str(e)[:200]}

    big = jnp.zeros(M, jnp.float32)
    t('scatter_add_colliding',
      lambda b, i, v: b.at[i].add(v), big, idx, vals)
    t('scatter_unique_perm',
      lambda i, v: jnp.zeros(n, jnp.float32).at[i].set(
          v, unique_indices=True), perm, vals)
    t('gather_random', lambda v, i: v[i], vals, perm)
    t('argsort_i32', lambda k: jnp.argsort(k), idx)
    t('sort_pair', lambda k, v: jax.lax.sort((k, v), num_keys=1),
      idx, vals)
    t('argsort_small_key', lambda k: jnp.argsort(k), small)
    t('cumsum', lambda v: jnp.cumsum(v), vals)

    # the counting-sort path (ops/radix.py): per-pass rank scan and the
    # full stable order, at the paint's two alphabet scales; plus the
    # same through the Pallas VMEM kernel — the first probe of whether
    # Mosaic custom calls lower over the axon tunnel at all
    from nbodykit_tpu.ops.radix import (stable_key_order,
                                        _pass_rank_hist)
    t('radix_rank_xla_D130', lambda k: _pass_rank_hist(k % 130, 130,
                                                       4096)[0], small)
    t('radix_order_D130', lambda k: stable_key_order(k % 130, 130,
                                                     engine='xla'),
      small)
    t('radix_order_D16513',
      lambda k: stable_key_order(k % 16513, 16513, engine='xla'), idx)
    try:
        from nbodykit_tpu.ops.radix_pallas import pass_rank_hist_pallas
        t('radix_rank_pallas_D130',
          lambda k: pass_rank_hist_pallas(k % 130, 130)[0], small)
    except Exception as e:          # lowering/import failure is itself
        out['radix_rank_pallas_D130'] = {"error": str(e)[:200]}  # data
    rec = {"metric": "prim_microbench_n%.0e" % n, "n": n,
           "platform": jax.devices()[0].platform, "prims": out}
    retr = [e for e in sup.events if e['kind'] == 'retries']
    degr = [e for e in sup.events if e['kind'] == 'degradations']
    if retr:
        rec['retries'] = len(retr)
    if degr:
        rec['degradations'] = [dict(e.get('detail', {}),
                                    rung=e.get('rung')) for e in degr]
    return _stamp(rec)


def run_fftbw(Nmesh=512, reps=3):
    """Isolated forward-rFFT bandwidth at a given mesh (verdict item:
    a stated GB/s vs the HBM roofline from a real measurement, not a
    phase-split difference). Uses the same dist_rfftn path production
    r2c uses (chunked past fft_chunk_bytes); the >=1024 case also
    times the eager lowmem driver the bench staged path uses.
    """
    jax = _setup_jax()
    import jax.numpy as jnp
    from nbodykit_tpu.parallel import dfft as _dfft

    field_bytes = 4.0 * Nmesh ** 3
    mk = jax.jit(lambda k: jax.random.uniform(
        k, (Nmesh, Nmesh, Nmesh), jnp.float32))
    rec = {"metric": "fftbw_nmesh%d" % Nmesh, "unit": "GB/s",
           "platform": jax.devices()[0].platform, "nmesh": Nmesh}

    def timed(fn):
        outs = fn()
        _sync(jax, outs)
        del outs
        t0 = time.time()
        for r in range(reps):
            outs = fn()
            _sync(jax, outs)
            del outs
        return (time.time() - t0) / reps

    if Nmesh < 1024:
        # in-jit path (what pm.r2c compiles to); NOT donated so one
        # persistent input serves every rep — no generation cost
        # inside the timed loop
        x = mk(jax.random.key(0))
        _sync(jax, x)
        f = jax.jit(lambda v: _dfft.dist_rfftn(v, None))
        dt = timed(lambda: f(x))
        rec['path'] = 'in-jit dist_rfftn'
    else:
        # the in-jit program holds ~4 full-mesh buffers at this size —
        # time the eager lowmem driver the staged bench path uses. It
        # consumes its input, so each rep regenerates the field; the
        # generation pass is timed separately and subtracted.
        def gen():
            return mk(jax.random.key(0))

        t_gen = timed(gen)

        def one():
            box = [gen()]
            return _dfft.rfftn_single_lowmem(box)

        dt = max(timed(one) - t_gen, 1e-9)
        rec['path'] = 'eager rfftn_single_lowmem'
        rec['gen_s'] = round(t_gen, 4)
    rec['rfft_s'] = round(dt, 4)
    # ~6 field passes across the three axis stages (transposed layout)
    rec['value'] = round(6 * field_bytes / dt / 1e9, 1)
    rec['frac_hbm_peak'] = round(rec['value'] / V5E_HBM_GBPS, 3)
    return _stamp(rec)


def run_fft_decomp(Nmesh=256, reps=3):
    """Slab-vs-pencil distributed rFFT on the process-visible
    multi-device mesh: the same ``pm.r2c`` program the tuner races
    (tune/space.py fft space), timed under both decompositions so the
    committed round files carry the knob's trajectory.  Needs >= 2
    devices (CPU: JAX_NUM_CPU_DEVICES=8); ``--pencil PXxPY`` picks the
    factorization, else the near-square default."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import nbodykit_tpu
    from nbodykit_tpu.parallel.runtime import (cpu_mesh,
                                               default_pencil_factor,
                                               mesh_size, tpu_mesh,
                                               use_mesh)
    from nbodykit_tpu.utils import is_mxu_backend
    mesh = tpu_mesh() if is_mxu_backend() else cpu_mesh()
    nproc = mesh_size(mesh)
    rec = {"metric": "fftdecomp_nmesh%d" % Nmesh, "unit": "s",
           "platform": jax.devices()[0].platform, "nmesh": Nmesh,
           "nproc": nproc}
    if nproc < 2:
        rec['error'] = ('fft decomp compare needs a multi-device mesh '
                        '(nproc=%d; on CPU set JAX_NUM_CPU_DEVICES)'
                        % nproc)
        return _stamp(rec)
    pencil = _FFT_OPTS.get('fft_pencil')
    if pencil:
        px, _, py = str(pencil).lower().partition('x')
        pxpy = (int(px), int(py))
        if pxpy[0] * pxpy[1] != nproc:
            raise SystemExit('--pencil %s does not cover %d devices'
                             % (pencil, nproc))
    else:
        pxpy = default_pencil_factor(nproc)
    rec['pencil'] = '%dx%d' % pxpy
    from nbodykit_tpu.pmesh import ParticleMesh
    with use_mesh(mesh):
        pm = ParticleMesh(Nmesh=Nmesh, BoxSize=1000.0,
                          dtype=_bench_mesh_dtype(Nmesh))
        x = jax.random.uniform(jax.random.key(7), pm.shape_real,
                               jnp.float32)
        x = jax.device_put(x, pm.sharding())
        _sync(jax, x)

        def timed():
            _sync(jax, pm.r2c(x))           # warm (compile) rep
            t0 = time.time()
            for _ in range(reps):
                _sync(jax, pm.r2c(x))
            return (time.time() - t0) / reps

        for name, opts in (('slab', {'fft_decomp': 'slab'}),
                           ('pencil', {'fft_decomp': 'pencil',
                                       'fft_pencil':
                                       '%dx%d' % pxpy})):
            with nbodykit_tpu.set_options(**opts):
                rec['%s_s' % name] = round(timed(), 4)
        from nbodykit_tpu.tune.resolve import tuned_snapshot
        rec['tuned'] = tuned_snapshot(nmesh=Nmesh, npart=0, dtype='f4',
                                      nproc=nproc)
    rec['value'] = min(rec['slab_s'], rec['pencil_s'])
    rec['winner'] = ('slab' if rec['slab_s'] <= rec['pencil_s']
                     else 'pencil')
    rec['pencil_speedup'] = round(rec['slab_s']
                                  / max(rec['pencil_s'], 1e-9), 3)
    return _stamp(rec)


#: The serving-posture exemplar fraction the trace benches run (and
#: measure overhead) under: request-level envelope spans for every
#: request (waterfalls stay complete), full kernel-span detail for a
#: sampled few.  Full-exemplar (the default, 1.0) is the debug
#: posture — its kernel spans sync eagerly inside `block_until_ready`
#: and cost 10-20% wall at serve request rates on a busy host.
SERVE_TRACE_EXEMPLAR = 0.02


def _flush_only_sync():
    """Scope the serving-posture tracing env: trace records are
    flushed (they survive a SIGKILL of the *process*) but not fsynced
    per span, and kernel spans are exemplar-sampled at
    :data:`SERVE_TRACE_EXEMPLAR` — the posture a latency-sensitive
    deployment would run, and the one the <5% overhead gate holds."""
    import contextlib

    @contextlib.contextmanager
    def _scope():
        keys = {'NBKIT_DIAGNOSTICS_SYNC': '0',
                'NBKIT_TRACE_EXEMPLAR': str(SERVE_TRACE_EXEMPLAR)}
        prev = {k: os.environ.get(k) for k in keys}
        os.environ.update(keys)
        try:
            yield
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    return _scope()


def _measure_overhead(once, n, reps=6):
    """Tracing overhead, measured honestly: warm every program cache
    with one throwaway run, then run ``reps`` mirrored off/on pairs in
    ABBA order (off,on,on,off,...) — host walls on a shared box drift
    monotonically over minutes, and the mirrored ordering cancels that
    drift to first order where a fixed off-then-on order would charge
    it all to one side.  Mean-of-sides over the mirrored sequence is
    the estimator; run-to-run wall noise on a busy 1-core host is
    ±10%, so anything under 4 mirrored pairs is a coin flip against
    the 5% gate."""
    import tempfile
    once(None)                  # warm every program cache first
    walls_off, walls_on = [], []
    for rep in range(int(reps)):
        legs = [False, True] if rep % 2 == 0 else [True, False]
        for traced in legs:
            if traced:
                walls_on.append(
                    once(tempfile.mkdtemp(prefix='nbkit-ovh-')))
            else:
                walls_off.append(once(None))
    wall_off = sum(walls_off) / len(walls_off)
    wall_on = sum(walls_on) / len(walls_on)
    return {'n': n, 'reps': int(reps), 'sync': 0,
            'exemplar': SERVE_TRACE_EXEMPLAR,
            'walls_on_s': [round(w, 3) for w in walls_on],
            'walls_off_s': [round(w, 3) for w in walls_off],
            'wall_on_s': round(wall_on, 3),
            'wall_off_s': round(wall_off, 3),
            'overhead': round((wall_on - wall_off)
                              / max(wall_off, 1e-9), 4)}


def _waterfall_stamp(tracedir):
    """Reduce a trace directory to the waterfall-completeness ledger
    the round record stamps (and the doctor's slo posture judges)."""
    try:
        from nbodykit_tpu.diagnostics import request_report
        from nbodykit_tpu.diagnostics.analyze import load_processes
        procs, _ = load_processes(tracedir)
        rep = request_report(procs)
        return {'traces': rep['traces'],
                'complete': rep['complete'],
                'complete_fraction': rep['complete_fraction'],
                'orphan_spans': rep['orphan_spans'],
                'incomplete': rep['incomplete'][:8],
                'critical_stages': rep['critical_stages'],
                'stage_totals_s': {k: round(v, 3) for k, v in
                                   rep['stage_totals_s'].items()}}
    except Exception as e:      # pragma: no cover - defensive
        return {'error': str(e)}


def run_serve_trace(n=1000, per_task=1, max_batch=8, seed=0):
    """The multi-tenant serving round: replay a deterministic
    ``n``-request synthetic trace (nbodykit_tpu.serve.synth — Zipf
    shape popularity, mixed priorities/deadlines, a slice of hopeless
    admission-rejects) through a live :class:`AnalysisServer` on the
    process-visible devices, and report requests/sec + real p50/p99.

    Fault injection rides ``NBKIT_FAULTS`` (the regress round injects
    ``serve.request.*`` faults so the record proves the fleet survives
    a mid-request tunnel death: exactly the faulted requests retry /
    degrade / resume, ``lost`` stays 0).  ``value`` is p99 seconds —
    lower is better, which is what regress.py trends."""
    jax = _setup_jax()
    import tempfile
    import nbodykit_tpu
    from nbodykit_tpu.resilience.faults import fault_counts, \
        reset_faults
    from nbodykit_tpu.serve import (AnalysisServer, BatchPolicy,
                                    generate_trace, replay)
    from nbodykit_tpu.tune.resolve import tuned_snapshot

    ndev = len(jax.devices())
    rec = {"metric": "servetrace_n%d" % n, "unit": "s",
           "platform": jax.devices()[0].platform, "requests": n,
           "ndevices": ndev, "per_task": per_task,
           "max_batch": max_batch, "seed": seed,
           "faults_spec": os.environ.get('NBKIT_FAULTS', '')}
    reset_faults()
    trace = generate_trace(n, seed=seed, deadline_s=600.0)
    tracedir = tempfile.mkdtemp(prefix='nbkit-strace-')
    t0 = time.time()
    with _flush_only_sync(), \
            nbodykit_tpu.set_options(diagnostics=tracedir):
        with AnalysisServer(per_task=per_task, max_queue=max(n, 16),
                            batch=BatchPolicy(max_batch=max_batch,
                                              max_delay_s=0.05)) as srv:
            replay(srv, trace, seed=seed)
            summary = srv.summary()
    rec['wall_s'] = round(time.time() - t0, 3)
    for key in ('submitted', 'completed', 'rejected', 'evicted',
                'failed', 'lost', 'retried', 'fault_degraded',
                'resumed', 'admit_degraded', 'workers', 'programs'):
        rec[key] = summary[key]
    rec['degraded'] = summary['fault_degraded']
    rec['rps'] = round(summary['rps'], 3)
    for key in ('p50_s', 'p99_s', 'mean_s'):
        rec[key] = round(summary[key], 5) \
            if summary[key] is not None else None
    rec['table'] = summary['by_class']
    # the queue-wait vs service-time split (the combined p50/p99
    # above stay for history continuity)
    for key in ('queue_p50_s', 'queue_p99_s', 'service_p50_s',
                'service_p99_s'):
        rec[key] = round(summary[key], 5) \
            if summary.get(key) is not None else None
    rec['slo'] = summary['slo']
    rec['waterfalls'] = _waterfall_stamp(tracedir)
    rec['faults_injected'] = {k: v for k, v in fault_counts().items()
                             if k.startswith('serve.')}
    rec['tuned'] = tuned_snapshot(nmesh=64, npart=50000, dtype='f4',
                                  nproc=per_task)

    # tracing overhead: the same closed-loop slam, fresh servers,
    # compile caches warm, with and without a live tracer
    n_ov = min(128, n)
    ov_trace = generate_trace(n_ov, seed=seed + 1, deadline_s=600.0)

    def _once(diag):
        reset_faults()
        with nbodykit_tpu.set_options(diagnostics=diag):
            w0 = time.time()
            with AnalysisServer(per_task=per_task,
                                max_queue=max(n_ov, 16),
                                batch=BatchPolicy(
                                    max_batch=max_batch,
                                    max_delay_s=0.05)) as s2:
                replay(s2, ov_trace, seed=seed + 1)
            return time.time() - w0

    with _flush_only_sync():
        rec['trace_overhead'] = _measure_overhead(_once, n_ov)
    errs = []
    if summary['lost']:
        errs.append('%d request(s) lost without a structured '
                    'verdict' % summary['lost'])
    if rec['trace_overhead']['overhead'] >= 0.05:
        errs.append('tracing overhead %.1f%% over the 5%% budget'
                    % (100.0 * rec['trace_overhead']['overhead']))
    wf = rec['waterfalls']
    if wf.get('traces') and wf.get('complete') != wf.get('traces'):
        errs.append('%d request waterfall(s) incomplete'
                    % (wf['traces'] - wf['complete']))
    if errs:
        rec['error'] = '; '.join(errs)
    rec['value'] = rec['p99_s'] if rec['p99_s'] is not None else -1.0
    return _stamp(rec)


def run_region_trace(n=200, fleets=2, per_task=1, seed=0,
                     interarrival_s=0.0):
    """The multi-fleet region round: replay a deterministic
    ``n``-item multi-tenant trace (per-tenant Zipf shapes, a
    repeat-request slice, a scripted mid-trace host arrival) through
    a live :class:`~nbodykit_tpu.serve.Region` fronting ``fleets``
    independent AnalysisServers, and report the full region posture:

    - **result cache**: hit count / hit rate, and a bit-identity
      check — one cached spectrum compared element-exact against a
      fresh recomputation on a virgin server;
    - **routing**: verdict counts (affinity / spill / catalog_home /
      rerouted_dead), with ≥1 structured spill expected under the
      closed-loop slam;
    - **elastic**: the mid-trace join, with the membership manifest's
      ``reformed_from``/``reformed_to`` stamps read back from disk;
    - **QoS**: per-class p50/p99 with the bulk tenant flooding at
      self-declared priority 2 — fair share holds (throttled > 0)
      and interactive requests stay unstarved (starved == 0);
    - ``lost == 0`` and ``unverified_as_verified == 0``, the two
      numbers the doctor FAILs on.

    ``value`` is the interactive-class p99 seconds — the number a
    bulk flood would inflate without fair share — lower is better."""
    jax = _setup_jax()
    import tempfile
    import numpy as np
    import nbodykit_tpu
    from nbodykit_tpu.parallel.runtime import cpu_mesh, use_mesh
    from nbodykit_tpu.resilience.faults import reset_faults
    from nbodykit_tpu.resilience.fleet import FleetCheckpointStore
    from nbodykit_tpu.serve import (AnalysisServer, QoSPolicy, Region,
                                    ResultCache, ServiceClass,
                                    generate_region_trace,
                                    replay_region)

    ndev = len(jax.devices())
    platform = jax.devices()[0].platform
    rec = {"metric": "regiontrace_n%d" % n, "unit": "s",
           "platform": platform, "requests": n, "fleets": fleets,
           "per_task": per_task, "seed": seed,
           "interarrival_s": float(interarrival_s),
           "faults_spec": os.environ.get('NBKIT_FAULTS', '')}
    reset_faults()

    def _fleet():
        # each fleet is an independent server; on CPU every fleet
        # fronts a 1-device sub-mesh (oversubscribing the host is
        # fine — the bench measures region mechanics, not FLOPs)
        if platform == 'cpu':
            with use_mesh(cpu_mesh(1)):
                return AnalysisServer(per_task=per_task,
                                      max_queue=max(n, 16))
        return AnalysisServer(per_task=per_task,
                              max_queue=max(n, 16))

    tmp = tempfile.mkdtemp(prefix='nbkit-region-')
    store = FleetCheckpointStore(os.path.join(tmp, 'ckpt'))
    qos = QoSPolicy(
        classes=[ServiceClass('interactive'),
                 ServiceClass('bulk', rate=16.0, burst=8)],
        tenants={'bulk-sweep': 'bulk'},
        default_class='interactive')
    trace = generate_region_trace(n, seed=seed, deadline_s=600.0,
                                  join_at=0.5)
    joins = []

    def _arrive(reg):
        joins.append(reg.join(_fleet()))

    tracedir = tempfile.mkdtemp(prefix='nbkit-rtrace-')
    with _flush_only_sync(), \
            nbodykit_tpu.set_options(diagnostics=tracedir):
        region = Region([('fleet-%d' % i, _fleet())
                         for i in range(int(fleets))],
                        result_cache=ResultCache(
                            os.path.join(tmp, 'results')),
                        qos=qos, spill_depth=2, checkpoint=store)
        t0 = time.time()
        # interarrival_s > 0 paces arrivals open-loop (Poisson) — the
        # load shape a latency SLO is judged under; 0 is the
        # closed-loop slam (right for routing/QoS mechanics, but it
        # charges pure queueing backlog to every latency number)
        replay_region(region, trace, seed=seed, on_join=_arrive,
                      interarrival_s=float(interarrival_s))
        region.drain(timeout=600)
        # bit-identity: one cached spectrum vs a fresh recomputation
        # on a virgin single-fleet server (same request, zero shared
        # state)
        probe = next((item['request'] for item in trace
                      if 'request' in item
                      and region.results.get(
                          item['request'].request_id) is not None
                      and region.results[
                          item['request'].request_id].ok), None)
        identical = None
        if probe is not None:
            from nbodykit_tpu.serve import AnalysisRequest
            cached = region.results[probe.request_id]
            srv = _fleet()
            fresh = srv.wait(srv.submit(AnalysisRequest.from_dict(
                dict(probe.to_dict(), request_id='region-bitcheck'))),
                timeout=300)
            srv.shutdown()
            identical = bool(
                fresh is not None and fresh.ok
                and np.array_equal(np.asarray(cached.y),
                                   np.asarray(fresh.y))
                and np.array_equal(np.asarray(cached.nmodes),
                                   np.asarray(fresh.nmodes)))
        summary = region.summary()
        region.shutdown()
    rec['wall_s'] = round(time.time() - t0, 3)
    for key in ('submitted', 'resolved', 'completed', 'rejected',
                'evicted', 'lost', 'fleet_count'):
        rec[key] = summary[key]
    cache = summary['result_cache'] or {}
    rec['result_hits'] = cache.get('hits', 0)
    rec['hit_rate'] = cache.get('hit_rate')
    rec['cache_corrupt'] = cache.get('corrupt', 0)
    rec['unverified_as_verified'] = cache.get('unverified_as_verified',
                                              0)
    rec['cache_bit_identical'] = identical
    routed = summary['routed']
    rec['routed'] = routed
    rec['spills'] = routed.get('spill', 0)
    rec['joins'] = summary['elastic']['joins']
    rec['rehomed'] = summary['elastic']['rehomed']
    man = store.latest_manifest('region')
    rec['reformed_from'] = man.get('reformed_from') if man else None
    rec['reformed_to'] = man.get('reformed_to') if man else None
    rec['throttled'] = summary['qos']['throttled']
    rec['starved'] = summary['qos']['starved']
    rec['table'] = summary['by_class']
    inter = summary['by_class'].get('interactive', {})
    rec['interactive_p50_s'] = inter.get('p50_s')
    rec['interactive_p99_s'] = inter.get('p99_s')
    rec['slo'] = summary['slo']
    rec['waterfalls'] = _waterfall_stamp(tracedir)

    # tracing overhead: a fresh single-join-free region, compile
    # caches warm, the same mixed-tenant slam with and without a
    # live tracer
    n_ov = min(128, n)
    ov_trace = generate_region_trace(n_ov, seed=seed + 1,
                                     deadline_s=600.0)

    def _ov_once(diag):
        reset_faults()
        with nbodykit_tpu.set_options(diagnostics=diag):
            # no QoS here on purpose: the pacer's token-bucket beats
            # couple the wall to scheduler jitter, which would swamp
            # the overhead signal this side-run exists to isolate
            reg = Region(
                [('ov-fleet-%d' % i, _fleet())
                 for i in range(int(fleets))],
                result_cache=ResultCache(tempfile.mkdtemp(
                    prefix='nbkit-ovh-cache-')),
                qos=None, spill_depth=2)
            w0 = time.time()
            replay_region(reg, ov_trace, seed=seed + 1)
            reg.drain(timeout=600)
            wall = time.time() - w0
            reg.shutdown()
            return wall

    with _flush_only_sync():
        rec['trace_overhead'] = _measure_overhead(_ov_once, n_ov)
    errs = []
    if summary['lost']:
        errs.append('%d request(s) lost without a structured verdict'
                    % summary['lost'])
    if rec['trace_overhead']['overhead'] >= 0.05:
        errs.append('tracing overhead %.1f%% over the 5%% budget'
                    % (100.0 * rec['trace_overhead']['overhead']))
    wf = rec['waterfalls']
    if wf.get('traces') and wf.get('complete') != wf.get('traces'):
        errs.append('%d request waterfall(s) incomplete'
                    % (wf['traces'] - wf['complete']))
    if rec['unverified_as_verified']:
        errs.append('%d unverified cache hit(s) served as verified'
                    % rec['unverified_as_verified'])
    if identical is False:
        errs.append('cached result NOT bit-identical to '
                    'recomputation')
    if errs:
        rec['error'] = '; '.join(errs)
    rec['value'] = rec['interactive_p99_s'] \
        if rec['interactive_p99_s'] is not None else -1.0
    return _stamp(rec)


def run_ingest(npart=400000, nmesh=64, chunk_rows=None, seed=0):
    """The ingestion-plane round: stream an on-disk catalog onto the
    device mesh (nbodykit_tpu.ingest, docs/INGEST.md) and measure the
    file -> painted-mesh bandwidth three ways —

    - cold: chunked read + overlapped H2D/paint (the production path),
    - warm: content-addressed cache hit (no file, no wire — straight
      to paint),
    - serial: same chunks with the overlap disabled (transfer, THEN
      paint) — the A/B that proves the double buffer earns its keep
      (``overlap_speedup`` = serial wall / cold wall),

    then replays the same catalog twice through a live AnalysisServer
    as ``data_ref`` requests so the record carries the e2e serving
    posture (completed / served-from-cache / lost).  The bit-identity
    contract is CHECKED, not assumed: the record refuses to report a
    warm GB/s for a mesh that differs from the cold one by a single
    bit.  ``host_peak_bytes`` is the high-water mark of host-resident
    chunk bytes — the proof the catalog was never host-resident.
    ``value`` is the cold GB/s (higher is better)."""
    jax = _setup_jax()
    import shutil
    import tempfile

    import numpy as np
    from nbodykit_tpu.ingest import (CatalogCache, DataRef,
                                     ingest_catalog, paint_cached,
                                     resolve_chunk_rows)
    from nbodykit_tpu.pmesh import ParticleMesh
    from nbodykit_tpu.resilience.faults import reset_faults
    from nbodykit_tpu.serve import (COMPLETED, AnalysisRequest,
                                    AnalysisServer)
    from nbodykit_tpu.tune.resolve import tuned_snapshot

    ndev = len(jax.devices())
    reset_faults()
    rng = np.random.RandomState(seed)
    pos = (rng.random_sample((npart, 3)) * 1000.0).astype('f4')
    tmpdir = tempfile.mkdtemp(prefix='bench-ingest-')
    try:
        path = os.path.join(tmpdir, 'catalog.bin')
        with open(path, 'wb') as fh:
            fh.write(pos.tobytes())
        del pos
        ref = DataRef(path, 'binary',
                      columns={'Position': 'Position'},
                      options={'dtype': [('Position', 'f4', (3,))]})
        nbytes = npart * 12
        chunk = resolve_chunk_rows(npart, ndev, chunk_rows)
        rec = {"metric": "ingest_n%d" % npart, "unit": "GB/s",
               "platform": jax.devices()[0].platform,
               "ndevices": ndev, "nmesh": nmesh, "rows": npart,
               "bytes": nbytes, "chunk_rows": chunk, "seed": seed}

        pm = ParticleMesh(Nmesh=nmesh, BoxSize=1000.0, dtype='f4')
        # warmup pass compiles the chunk-paint program so the timed
        # cold/serial passes measure streaming, not jit
        ingest_catalog(ref, pm, chunk_rows=chunk, overlap=True)

        reps = int(os.environ.get('BENCH_REPS', '3') or 3)
        colds, serials = [], []
        for _ in range(reps):
            colds.append(ingest_catalog(
                ref, pm, chunk_rows=chunk, overlap=True)[2])
            serials.append(ingest_catalog(
                ref, pm, chunk_rows=chunk, overlap=False)[2])
        cache = CatalogCache()
        cold_field, entry, cold = ingest_catalog(
            ref, pm, chunk_rows=chunk, overlap=True, cache=cache)
        colds.append(cold)
        warms, warm_field = [], None
        for _ in range(reps):
            warm_field, _, w = ingest_catalog(
                ref, pm, chunk_rows=chunk, overlap=True, cache=cache)
            warms.append(w)
            if not w['cache_hit']:
                rec['error'] = 'repeat ingest missed the catalog cache'
        if not np.array_equal(np.asarray(cold_field),
                              np.asarray(warm_field)):
            rec['error'] = ('cache-hit mesh differs from cold mesh — '
                            'bit-identity contract violated')
        # replaying the resident chunks alone (no file, no H2D) is the
        # cache's steady-state rate; the warm passes already measured
        # it end-to-end through ingest_catalog
        t0 = time.time()
        jax.block_until_ready(paint_cached(pm, entry))
        rec['replay_s'] = round(time.time() - t0, 5)
        cold_s = min(s['seconds'] for s in colds)
        warm_s = min(s['seconds'] for s in warms)
        serial_s = min(s['seconds'] for s in serials)
        rec['reps'] = reps
        rec['cold_s'] = round(cold_s, 5)
        rec['warm_s'] = round(warm_s, 5)
        rec['serial_s'] = round(serial_s, 5)
        rec['cold_gbs'] = round(nbytes / 1e9 / max(cold_s, 1e-9), 4)
        rec['warm_gbs'] = round(nbytes / 1e9 / max(warm_s, 1e-9), 4)
        rec['serial_gbs'] = round(nbytes / 1e9 / max(serial_s, 1e-9),
                                  4)
        rec['overlap_speedup'] = round(serial_s / max(cold_s, 1e-9), 3)
        rec['chunks'] = cold['chunks']
        rec['host_peak_bytes'] = max(
            s['host_peak_bytes'] for s in colds + serials)
        if rec['host_peak_bytes'] >= nbytes and cold['chunks'] > 1:
            rec['error'] = ('host peak %d bytes >= catalog %d bytes: '
                            'the stream went host-resident'
                            % (rec['host_peak_bytes'], nbytes))
        cstats = cache.stats()
        rec['cache_hits'] = cstats['hits']
        rec['cache_evictions'] = cstats['evictions']
        cache.clear()
        del cold_field, warm_field, entry

        # e2e: the same catalog served twice as data_ref requests —
        # sequentially, so the second must ride the worker's
        # on-device cache (cache-affine placement keys on the path)
        with AnalysisServer(per_task=1, max_queue=16) as srv:
            d = ref.to_dict()
            results = [srv.wait(srv.submit(AnalysisRequest(
                nmesh=nmesh, data_ref=d, deadline_s=600.0)))
                for _ in range(2)]
            summary = srv.summary()
        rec['serve_completed'] = sum(
            1 for r in results if r.status == COMPLETED)
        rec['serve_cache_hits'] = summary['ingest_cache_hits']
        rec['serve_lost'] = summary['lost']
        rec['serve_ingest_gb'] = summary['ingest_gb']
        rec['tuned'] = tuned_snapshot(nmesh=nmesh, npart=npart,
                                      dtype='f4', nproc=ndev)
        rec['value'] = rec['cold_gbs']
        return _stamp(rec)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def run_forward(nmesh=32, npart=None, steps=2, seed=0):
    """The differentiable forward-model round (docs/FORWARD.md): one
    LPT+PM pipeline priced forward AND backward, with the gradient
    CHECKED against finite differences and the recovery CHECKED
    against the classical baseline.

    Four measurements on the process-visible device mesh (f8 — the
    finite-difference probe needs the full mantissa):

    - *forward*: jitted ``density(modes)`` wall seconds (min of reps);
    - *backward*: jitted ``grad(loss)`` wall seconds — ``overhead`` is
      the backward/forward ratio reverse-mode costs on this pipeline;
    - *gradient check*: a directional derivative <grad, d> vs the
      central finite difference at eps=1e-6.  ``grad_check_ok`` is the
      stamp the doctor turns into a FAIL verdict — a forward model
      whose gradient is wrong is not differentiable, however fast;
    - *recovery*: Adam on the whitenoise posterior
      (nbodykit_tpu.forward.recover, linear-theory initialized) vs
      FFTRecon (LGS) of the evolved particles, both scored by
      whole-field cross-correlation with the truth modes.
      ``beats_baseline`` must hold — the point of the gradient is to
      beat the classical estimator.

    ``npart`` defaults to nmesh^3 (lattice == force mesh, which the
    linear-theory recovery init requires); ``value`` is the backward
    wall seconds (lower is better)."""
    jax = _setup_jax()
    jax.config.update('jax_enable_x64', True)
    import contextlib

    from nbodykit_tpu.forward import (ForwardModel, fftrecon_baseline,
                                      linear_init, make_loss,
                                      mean_cross_correlation, recover)
    from nbodykit_tpu.parallel.runtime import (cpu_mesh, mesh_size,
                                               tpu_mesh, use_mesh)
    from nbodykit_tpu.pmesh import memory_plan
    from nbodykit_tpu.tune.resolve import tuned_snapshot
    from nbodykit_tpu.utils import is_mxu_backend

    mesh = tpu_mesh() if is_mxu_backend() else cpu_mesh()
    nproc = mesh_size(mesh)
    if npart is None:
        npart = int(nmesh) ** 3
    ng = int(round(float(npart) ** (1.0 / 3.0)))
    if ng ** 3 != npart:
        raise SystemExit('--forward NPART must be a cube ng^3 '
                         '(got %d)' % npart)
    rec = {"metric": "forward_mesh%d_n%d" % (nmesh, npart),
           "unit": "s", "platform": jax.devices()[0].platform,
           "nproc": nproc, "nmesh": nmesh, "npart": npart,
           "pm_steps": int(steps), "seed": seed, "dtype": "f8"}
    ctx = use_mesh(mesh) if nproc >= 2 else contextlib.nullcontext()
    with ctx:
        import jax.numpy as jnp
        model = ForwardModel(nmesh, npart, BoxSize=1000.0,
                             pm_steps=int(steps), dtype='f8')
        rec['paint_method'] = model.paint_cfg.get('paint_method')
        rec['adjoint_mode'] = model.paint_cfg.get('adjoint_mode')
        plan = memory_plan(nmesh, npart, ndevices=nproc, dtype='f8',
                           workload='forward', pm_steps=int(steps))
        rec['plan_peak_bytes'] = int(plan['peak_bytes'])
        rec['grad_residual_bytes'] = int(
            plan.get('grad_residual_bytes', 0))

        truth = model.linear_modes(seed)
        density = jax.jit(model.density)
        t0 = time.time()
        obs = jax.block_until_ready(density(truth))
        rec['compile_forward_s'] = round(time.time() - t0, 4)
        loss = make_loss(model, obs, noise_std=0.1)
        # one jit per bench invocation, timed across every rep below —
        # the cache outlives the loop it serves  # nbkl: disable=NBK202
        grad = jax.jit(jax.grad(loss))
        w0 = model.lattice.c2r(model.lattice.generate_whitenoise(
            seed + 1)) * 0.05
        t0 = time.time()
        g0 = jax.block_until_ready(grad(w0))
        rec['compile_grad_s'] = round(time.time() - t0, 4)

        reps = int(os.environ.get('BENCH_REPS', '3') or 3)
        fwd_s, bwd_s = [], []
        for _ in range(reps):
            t0 = time.time()
            jax.block_until_ready(density(truth))
            fwd_s.append(time.time() - t0)
            t0 = time.time()
            jax.block_until_ready(grad(w0))
            bwd_s.append(time.time() - t0)
        rec['reps'] = reps
        rec['forward_s'] = round(min(fwd_s), 5)
        rec['grad_s'] = round(min(bwd_s), 5)
        rec['grad_overhead'] = round(
            min(bwd_s) / max(min(fwd_s), 1e-9), 3)

        # directional finite-difference check: eps=1e-6 sits below the
        # CIC window's kink noise at f8 (tests/test_forward.py carries
        # the per-kernel adjoint checks; this is the deployed-pipeline
        # spot check the round commits as evidence)
        d = model.lattice.c2r(model.lattice.generate_whitenoise(
            seed + 2))
        d = d / jnp.sqrt(jnp.sum(d * d))
        eps = 1e-6
        ljit = jax.jit(loss)
        fd = (float(ljit(w0 + eps * d)) - float(ljit(w0 - eps * d))) \
            / (2.0 * eps)
        dot = float(jnp.sum(g0 * d))
        rel = abs(fd - dot) / max(abs(fd), 1e-300)
        rec['grad_check'] = {'eps': eps, 'fd': fd, 'grad_dot': dot,
                             'rel_err': round(rel, 9)}
        rec['grad_check_ok'] = bool(rel < 1e-4)

        # recovery vs the classical baseline, both scored against the
        # truth by whole-field cross-correlation on the lattice
        adam_steps = int(os.environ.get('BENCH_FORWARD_ADAM', '80')
                         or 80)
        white, losses = recover(model, obs, steps=adam_steps, lr=0.1,
                                noise_std=0.1,
                                white0=linear_init(model, obs)
                                if ng == nmesh else None)
        lat = model.lattice
        r_rec = float(mean_cross_correlation(
            lat, model.modes_from_white(white), truth))
        pos, _mom = model.evolve(truth)
        base = fftrecon_baseline(model, pos)
        r_base = float(mean_cross_correlation(lat, base, truth))
        rec['recovery'] = {
            'adam_steps': adam_steps,
            'loss_first': round(losses[0], 3),
            'loss_last': round(losses[-1], 3),
            'r_recovered': round(r_rec, 5),
            'r_fftrecon': round(r_base, 5),
            'beats_baseline': bool(r_rec > r_base),
        }
        rec['tuned'] = tuned_snapshot(nmesh=nmesh, npart=npart,
                                      dtype='f8', nproc=nproc)
        rec['value'] = rec['grad_s']
    return _stamp(rec)


def run_bispectrum(nmesh=32, npart=20000, nbins=3, seed=0):
    """The higher-order-statistics round (docs/BISPECTRUM.md): the
    Scoccimarro FFT estimator raced against the blocked direct
    pairwise-summation path on the SAME deterministic catalog — the
    first FLOPs-bound workload in the suite.

    The record stamps the per-shape crossover evidence the ``bspec``
    tune space turns into cached winners:

    - *fft_s* / *direct_s*: full-estimator wall seconds (paint + r2c +
      triangle stream vs pairblock mode sums + host combination), min
      of BENCH_REPS;
    - *crossover*: the speedup ratio and which path won AT THIS SHAPE
      (the direct path's O(Npart x Nk) dense matmuls beat the FFT's
      mesh pipeline only where the MXU can stream them — per-platform,
      never guessed);
    - *agreement*: with ``2 (nbins+1) <= nmesh/2`` no aliased triangle
      exists, the mod-N and true closures coincide, and the two paths
      measure the SAME statistic: ``ntri`` must match bit for bit and
      B to window/resolution tolerance.  ``agree_ok`` False is the
      doctor's FAIL — two estimators of one statistic disagreeing
      means one of them is wrong.

    The catalog carries an imprinted non-Gaussian weight field (a
    squared cosine sum) so the bispectrum signal dominates shot noise;
    ``value`` is the winning path's wall seconds."""
    jax = _setup_jax()
    import contextlib
    import numpy as np

    from nbodykit_tpu.algorithms.bispectrum import (direct_bispectrum,
                                                    fft_bispectrum)
    from nbodykit_tpu.parallel.runtime import (cpu_mesh, mesh_size,
                                               tpu_mesh, use_mesh)
    from nbodykit_tpu.pmesh import ParticleMesh, memory_plan
    from nbodykit_tpu.tune.resolve import (resolve_bispectrum,
                                           tuned_snapshot)
    from nbodykit_tpu.utils import is_mxu_backend

    mesh = tpu_mesh() if is_mxu_backend() else cpu_mesh()
    nproc = mesh_size(mesh)
    L = 1000.0
    rec = {"metric": "bispectrum_mesh%d_n%d_b%d"
                     % (nmesh, npart, nbins),
           "unit": "s", "platform": jax.devices()[0].platform,
           "nproc": nproc, "nmesh": nmesh, "npart": npart,
           "nbins": nbins, "seed": seed}
    rng = np.random.RandomState(seed + 11)
    pos = rng.uniform(0.0, L, size=(npart, 3))
    # imprinted non-Gaussian weights: squared sum of low-|q| cosines
    g = np.zeros(npart)
    for m in [(1, 0, 0), (0, 1, 0), (0, 0, 1), (1, 1, 0), (0, 1, 1),
              (1, 0, 1), (2, 0, 0), (1, 1, 1)]:
        ph = rng.uniform(0, 2 * np.pi)
        g += 0.4 * np.cos(2 * np.pi * (pos @ np.array(m)) / L + ph)
    w = (1.0 + 0.5 * g) ** 2

    ctx = use_mesh(mesh) if nproc >= 2 else contextlib.nullcontext()
    with ctx:
        import jax.numpy as jnp
        comm = mesh if nproc >= 2 else None
        cfg = resolve_bispectrum(nmesh=nmesh, npart=npart,
                                 nproc=nproc)
        tile = int(cfg['pairblock_tile'])
        rec['pairblock_tile'] = tile
        rec['resolved_method'] = cfg['bspec_method']
        pm = ParticleMesh(Nmesh=nmesh, BoxSize=L, dtype='f4',
                          comm=comm)
        posj = jnp.asarray(pos, pm.dtype)
        wj = jnp.asarray(w, pm.dtype)
        # match the direct path's (1/W) sum_j w_j e^{-ikx} convention
        scale = float(pm.Ntot) / float(w.sum())

        def fft_once():
            delta = pm.paint(posj, wj) * scale
            return fft_bispectrum(pm, pm.r2c(delta), nbins)

        def direct_once():
            return direct_bispectrum(posj, wj, L, nbins, tile=tile,
                                     comm=comm)

        reps = int(os.environ.get('BENCH_REPS', '3') or 3)
        rec['reps'] = reps
        t0 = time.time()
        Bf, ntri_f = fft_once()                   # warm/compile rep
        rec['compile_fft_s'] = round(time.time() - t0, 4)
        t0 = time.time()
        Bd, ntri_d = direct_once()
        rec['compile_direct_s'] = round(time.time() - t0, 4)
        fft_s, direct_s = [], []
        for _ in range(reps):
            t0 = time.time()
            fft_once()
            fft_s.append(time.time() - t0)
            t0 = time.time()
            direct_once()
            direct_s.append(time.time() - t0)
        rec['fft_s'] = round(min(fft_s), 5)
        rec['direct_s'] = round(min(direct_s), 5)
        rec['crossover'] = {
            'fft_s': rec['fft_s'], 'direct_s': rec['direct_s'],
            'speedup_fft_over_direct': round(
                rec['direct_s'] / max(rec['fft_s'], 1e-9), 3),
            'faster': 'fft' if rec['fft_s'] <= rec['direct_s']
                      else 'direct'}

        # cross-path agreement: valid whenever no triangle can wrap
        overlap = 2 * (nbins + 1) <= nmesh // 2
        rec['closure_overlap'] = bool(overlap)
        if overlap:
            both = ~(np.isnan(Bf) | np.isnan(Bd))
            ntri_ok = bool(np.array_equal(
                np.nan_to_num(ntri_f, nan=-1.0),
                np.nan_to_num(ntri_d, nan=-1.0)))
            bscale = float(np.abs(Bd[both]).max()) if both.any() \
                else 1.0
            b_max_rel = float(np.abs(Bf[both] - Bd[both]).max()
                              / max(bscale, 1e-300)) if both.any() \
                else 0.0
            rec['agreement'] = {'ntri_bit_identical': ntri_ok,
                                'b_max_rel': round(b_max_rel, 6),
                                'b_scale': bscale,
                                'cells_compared': int(both.sum())}
            rec['agree_ok'] = bool(ntri_ok and b_max_rel < 0.1)
        plan_f = memory_plan(nmesh, npart, ndevices=nproc,
                             workload='bispectrum', nbins=nbins,
                             bspec_method='fft')
        plan_d = memory_plan(nmesh, npart, ndevices=nproc,
                             workload='bispectrum', nbins=nbins,
                             bspec_method='direct',
                             pairblock_tile=tile)
        rec['plan_fft_peak_bytes'] = int(plan_f['peak_bytes'])
        rec['plan_direct_peak_bytes'] = int(plan_d['peak_bytes'])
        rec['tuned'] = tuned_snapshot(nmesh=nmesh, npart=npart,
                                      nproc=nproc)
        rec['value'] = min(rec['fft_s'], rec['direct_s'])
    return _stamp(rec)


def run_integrity(nmesh=64, npart=200000, reps=3, seed=7):
    """The data-integrity round (docs/INTEGRITY.md): price the tier-0
    guards and prove the detect -> retry -> deliver loop end to end.

    Two measurements on the process-visible device mesh:

    - *overhead*: the eager paint + r2c pipeline (every guard lives on
      the eager path) timed under ``integrity='off'`` vs ``'cheap'`` —
      ``overhead`` is the relative cost of the mass / Parseval / a2a
      fold checks;
    - *detection*: the same pipeline once under a Supervisor with
      ``integrity='cheap'``.  When ``NBKIT_FAULTS`` carries a
      ``corrupt`` rule (the regress round injects
      ``a2a.payload@1:corrupt``) the owning guard raises a classified
      IntegrityError, the supervisor strikes the rank and retries
      exactly once, and the retry runs clean because injected rules
      fire once — so the record proves the corruption was caught AND
      the result was still delivered.

    The record stamps ``integrity: {violations, retried}`` — the
    ledger regress.py's integrity posture and the doctor judge.
    ``value`` is the guarded (cheap) wall seconds."""
    jax = _setup_jax()
    import nbodykit_tpu
    from nbodykit_tpu.parallel.runtime import (cpu_mesh, mesh_size,
                                               tpu_mesh, use_mesh)
    from nbodykit_tpu.pmesh import ParticleMesh
    from nbodykit_tpu.resilience import (Supervisor, reset_faults,
                                         reset_integrity,
                                         violation_counts)
    from nbodykit_tpu.tune.resolve import tuned_snapshot
    from nbodykit_tpu.utils import is_mxu_backend
    import contextlib

    mesh = tpu_mesh() if is_mxu_backend() else cpu_mesh()
    nproc = mesh_size(mesh)
    rec = {"metric": "integrity_nmesh%d" % nmesh, "unit": "s",
           "platform": jax.devices()[0].platform, "nmesh": nmesh,
           "npart": npart, "nproc": nproc, "seed": seed,
           "faults_spec": os.environ.get('NBKIT_FAULTS', '')}
    reset_faults()
    reset_integrity()
    ctx = use_mesh(mesh) if nproc >= 2 else contextlib.nullcontext()
    with ctx:
        pm = ParticleMesh(Nmesh=nmesh, BoxSize=1000.0, dtype='f4')
        import jax.numpy as jnp
        pos = _make_pos(jax, jnp, npart, 1000.0, seed=seed)
        _sync(jax, pos)

        def once():
            # eager on purpose: the tier-0 guards live on the eager
            # dispatch path (a data-dependent raise cannot live under
            # trace), so this is the surface they price and defend
            field = pm.paint(pos)
            out = pm.r2c(field)
            _sync(jax, out)
            return out

        # detection FIRST: any configured corrupt rule is consumed
        # here (rules fire once per process), so the timed passes
        # below measure clean guarded reps, not injected failures
        v0 = violation_counts()['violations']
        sup = Supervisor('bench.integrity')
        with nbodykit_tpu.set_options(integrity='cheap'):
            sup.run(once)
        vc = violation_counts()
        rec['integrity'] = {
            'violations': vc['violations'] - v0,
            'retried': sum(1 for e in sup.events
                           if e.get('kind') == 'integrity_retries')}
        rec['violation_sites'] = vc['by_site']

        def timed():
            once()                              # warm (compile) rep
            t0 = time.time()
            for _ in range(reps):
                once()
            return (time.time() - t0) / reps

        with nbodykit_tpu.set_options(integrity='off'):
            rec['off_s'] = round(timed(), 5)
        with nbodykit_tpu.set_options(integrity='cheap'):
            rec['cheap_s'] = round(timed(), 5)
    rec['reps'] = reps
    rec['overhead'] = round(rec['cheap_s'] / max(rec['off_s'], 1e-9)
                            - 1.0, 4)
    rec['tuned'] = tuned_snapshot(nmesh=nmesh, npart=npart, dtype='f4',
                                  nproc=nproc)
    rec['value'] = rec['cheap_s']
    return _stamp(rec)


def _paint_method_options(method, Nmesh, Npart):
    """``set_options`` kwargs selecting one paint configuration by
    name.

    Accepts (1) any REGISTERED tuner candidate name for this shape
    ('scatter', 'sort', 'segsum-radix', 'streams4', 'mxu-radix-xla',
    ... — tune/space.py), so bench measurements and trials select
    identical programs; (2) the legacy suffix grammar
    'mxu:ORDER[:DEPOSIT]', 'segsum:ORDER' and 'streams:K'.  Every
    option a configuration does NOT pin is reset to its default — a
    prior call in this process must not leak engines into a
    differently-labeled measurement.
    """
    from nbodykit_tpu.tune.space import registered_paint_candidates
    base = {'paint_order': 'auto', 'paint_deposit': 'auto',
            'paint_streams': 'auto',
            'paint_chunk_size': 1024 * 1024 * 16}
    for cand in registered_paint_candidates(Nmesh, Npart):
        if cand.name == method:
            opts = dict(base)
            opts.update(cand.options)
            # an explicit --mesh-dtype outranks the candidate's
            # storage default: 'scatter --mesh-dtype bf16' means
            # bf16 scatter, not the registered f4 variant
            if _FFT_OPTS.get('mesh_dtype'):
                opts['mesh_dtype'] = _FFT_OPTS['mesh_dtype']
            return opts
    opts = dict(base)
    if ':' in method:
        parts = method.split(':')
        method = parts[0]
        if method == 'streams':
            opts['paint_streams'] = int(parts[1])
        else:
            opts['paint_order'] = parts[1]
        if len(parts) > 2:
            opts['paint_deposit'] = parts[2]
    opts['paint_method'] = method
    if _FFT_OPTS.get('mesh_dtype'):
        opts['mesh_dtype'] = _FFT_OPTS['mesh_dtype']
    return opts


def run_paint(Nmesh, Npart, method='scatter', reps=3):
    """Paint-only microbenchmark (the #1 perf risk, SURVEY §7).

    ``method`` is a registered tuner candidate name or a legacy
    'METHOD[:ORDER[:DEPOSIT]]' / 'streams:K' spec
    (:func:`_paint_method_options`).  The record carries the summed
    painted mass (``mass_sum``) so gates can reject a kernel that
    lowers but deposits NaNs.
    """
    jax = _setup_jax()
    import jax.numpy as jnp
    import nbodykit_tpu
    from nbodykit_tpu.pmesh import ParticleMesh

    method_label = method      # metric key keeps the candidate name
    nbodykit_tpu.set_options(**_paint_method_options(
        method, Nmesh, Npart))
    pm = ParticleMesh(Nmesh=Nmesh, BoxSize=1000.0,
                      dtype=_bench_mesh_dtype(Nmesh))
    pos = _make_pos(jax, jnp, Npart, 1000.0)
    fn = jax.jit(lambda p: pm.paint(p, 1.0, resampler='cic',
                                    return_dropped=True)[0])
    dt, _ = _time_fn(jax, fn, (pos,), reps,
                     label='paint_%s' % method_label)
    mass_sum = float(jnp.sum(fn(pos)))
    from nbodykit_tpu.tune.resolve import tuned_snapshot
    return _stamp({
        "metric": "paint_wallclock_nmesh%d_npart%.0e_%s"
                  % (Nmesh, Npart, method_label),
        "value": round(dt, 4), "unit": "s",
        "mpart_per_s": round(Npart / dt / 1e6, 1),
        "mass_sum": mass_sum,
        "platform": jax.devices()[0].platform,
        "tuned": tuned_snapshot(nmesh=Nmesh, npart=Npart, dtype='f4',
                                nproc=pm.nproc),
    })


def run_paint_all(Nmesh, Npart, reps=3):
    """Every registered paint candidate at one shape, one record each
    (the smoke gate's CI sweep and the pre-hardware baseline for
    ROADMAP #1).  A candidate that raises is recorded with an
    ``error`` field instead of killing the sweep — the gate decides.
    """
    from nbodykit_tpu.tune.space import registered_paint_candidates
    out = {}
    for cand in registered_paint_candidates(Nmesh, Npart):
        try:
            out[cand.name] = run_paint(Nmesh, Npart, cand.name,
                                       reps=reps)
        except Exception as e:                      # gate fodder
            out[cand.name] = {"error": str(e)[:300]}
    return out


# ---------------------------------------------------------------------------
# worker: runs the whole ladder in ONE process, flushing after each step

def _flush_detail(detail):
    tmp = DETAIL_PATH + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(detail, f, indent=1)
    os.replace(tmp, DETAIL_PATH)


def _stage_partial(rec, **extra):
    """Merge one in-progress config record into BENCH_STAGED.json
    (atomic tmp+rename, keyed by metric).

    Called BEFORE the final device sync/timing barrier: round 5 lost
    the 1024^3/1e7 record because the tunnel died mid-timing and every
    flush ran only after — now the warmed measurement (first-run wall,
    compile included) survives any death during the timed reps, and
    the completed record overwrites it in place.
    """
    try:
        with open(STAGED_PATH) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {"results": {}}
    rec = dict(rec)
    rec.update(extra)
    rec['staged_at'] = time.strftime('%Y-%m-%dT%H:%M:%SZ',
                                     time.gmtime())
    data['results'][str(rec.get('metric', '?'))] = rec
    tmp = STAGED_PATH + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(data, f, indent=1)
    os.replace(tmp, STAGED_PATH)


def _cache_tpu_result(rec):
    """Merge one real-TPU config record into the committed cache
    (atomic; keyed by metric, latest wins)."""
    if rec.get('platform') not in TPU_PLATFORMS \
            or rec.get('platform') == 'cpu':
        # the explicit cpu check is a belt against test harnesses that
        # widen TPU_PLATFORMS (a CPU rehearsal once leaked a cpu record
        # into the committed TPU cache this way)
        return
    try:
        with open(TPU_CACHE_PATH) as f:
            cache = json.load(f)
    except (OSError, ValueError):
        cache = {"results": {}}
    rec = dict(rec)
    _stamp(rec)     # keep the original measurement time on re-cache
    if rec.get('error'):
        return  # an error-flagged timing must never become a headline
    prev = cache['results'].get(rec['metric'])
    if prev and not prev.get('error'):
        pv = prev.get('value', -1)
        if 0 < pv < rec.get('value', -1):
            return  # keep the fastest VALID measurement of this config
        if pv == rec.get('value', -1) and prev.get('phases') \
                and not rec.get('phases'):
            return  # an equal-value tie only replaces to ADD phase
            # data (the same-run refresh), never to drop it
    cache['results'][rec['metric']] = rec
    tmp = TPU_CACHE_PATH + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(cache, f, indent=1)
    os.replace(tmp, TPU_CACHE_PATH)


def _cache_cpu_baseline(rec):
    """Merge one CPU config record into the committed same-config
    baseline store BASELINE_CPU.json (atomic; keyed by metric)."""
    if rec.get('platform') != 'cpu' or rec.get('value', -1) <= 0 \
            or rec.get('error'):
        return
    path = os.path.join(HERE, 'BASELINE_CPU.json')
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {"results": {}}
    prev = data['results'].get(rec['metric'])
    if prev and prev.get('value', -1) == rec['value'] \
            and prev.get('phases') and not rec.get('phases'):
        return  # equal-value tie must not drop phase data
    if prev and 0 < prev.get('value', -1) < rec['value']:
        # keep the FASTEST CPU measurement: the baseline is what the
        # CPU can do, and runs taken while other workers contend for
        # the core would otherwise inflate vs_baseline in our favor
        return
    rec = dict(rec)
    _stamp(rec)     # keep the original measurement time on re-cache
    data['results'][rec['metric']] = rec
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(data, f, indent=1)
    os.replace(tmp, path)


def _best_cached_tpu():
    try:
        with open(TPU_CACHE_PATH) as f:
            cache = json.load(f)
    except (OSError, ValueError):
        return None
    best = None
    for rec in cache.get('results', {}).values():
        if not str(rec.get('metric', '')).startswith('fftpower'):
            continue  # the headline is the flagship FFTPower ladder
        if rec.get('platform') not in TPU_PLATFORMS \
                or rec.get('platform') == 'cpu':
            continue  # the claim made from this cache is 'real-TPU
            # measurement' — filter at read time too, not just write
        if rec.get('value') and rec.get('value', -1) > 0:
            # prefer the largest mesh (metric names sort by Nmesh
            # numerically via the recorded nmesh field if present)
            key = (rec.get('nmesh', 0), rec.get('npart', 0))
            if best is None or key >= (best.get('nmesh', 0),
                                       best.get('npart', 0)):
                best = rec
    return best


def cmd_worker():
    from nbodykit_tpu.resilience import Preempted
    detail = {"state": "starting", "t0": time.time(), "probe": None,
              "paint": [], "configs": [], "done": False}
    _flush_detail(detail)

    def note(msg):
        print("[worker %.0fs] %s" % (time.time() - detail['t0'], msg),
              flush=True)

    # tiniest possible op first: if the tunnel is wedged we hang HERE,
    # with state=probing on disk for the orchestrator to report
    detail['state'] = 'probing'
    _flush_detail(detail)
    try:
        jax = _setup_jax()
        import jax.numpy as jnp
        d = jax.devices()
        x = jnp.ones((64, 64))
        s = float((x @ x).sum())
        assert s == 64.0 * 64 * 64
        detail['probe'] = {"platform": d[0].platform,
                           "kind": getattr(d[0], 'device_kind', '?'),
                           "n": len(d),
                           "dt": round(time.time() - detail['t0'], 1)}
        note("probe ok: %s" % detail['probe'])
    except Exception as e:
        detail['probe'] = {"error": str(e)[:300]}
        detail['state'] = 'probe_failed'
        detail['done'] = True
        _flush_detail(detail)
        note("probe failed: %s" % e)
        return 1
    detail['state'] = 'running'
    _flush_detail(detail)

    # paint microbench, all three kernels, at TWO scales: the winner at
    # 256^3/1e6 paints the small rungs, the winner at 512^3/1e7 paints
    # the >=512 rungs (kernel ranking is scale-dependent: scatter is
    # latency-bound per element, sort pays O(n log^2 n) bitonic passes,
    # mxu pays a fixed matmul/onehot overhead that amortizes at scale)
    def tune(Nmesh, Npart):
        results = {}
        for method in ('scatter', 'sort', 'mxu'):
            try:
                p = run_paint(Nmesh, Npart, method=method)
                detail['paint'].append(p)
                note("paint micro: %s" % p)
                results[method] = p['value']
            except Exception as e:
                detail['paint'].append({"method": method,
                                        "error": str(e)[:300]})
                note("paint micro (%s) failed: %s" % (method, e))
            _flush_detail(detail)
        # fastest SUCCEEDED method (a failed kernel must never paint
        # the ladder); scatter only when all failed
        return min(results, key=results.get) if results else 'scatter'

    best_small = tune(256, 1_000_000)
    on_tpu = detail['probe'].get('platform') in TPU_PLATFORMS
    # the >=512 rungs run with the KNOWN-SAFE scatter kernel first
    # (round-4: an oversized compile can kill the axon remote-compile
    # helper and wedge every later compile — the guaranteed-compilable
    # ladder must land before any risky 512-scale mxu/sort compile is
    # attempted); the 512-scale autotune + winner re-runs follow as a
    # bonus pass
    best_big = 'scatter' if on_tpu else best_small
    detail['paint_method'] = {'small': best_small, 'big': best_big}
    note("ladder paint methods: <512 %s, >=512 %s (safe first pass)"
         % (best_small, best_big))
    _flush_detail(detail)

    # smallest-first ladder up to the north-star config; every step is
    # sized to finish (clean Python exceptions, e.g. OOM, do NOT wedge
    # the tunnel — only kills do, and nobody kills us)
    if on_tpu:
        ladder = [(128, 100_000), (256, 1_000_000), (512, 10_000_000),
                  (1024, 10_000_000), (1024, 100_000_000)]
    else:
        # CPU fallback (wedged tunnel): clearly-marked scale proof AND
        # the same-config vs_baseline denominators — so the ladder
        # matches the TPU rungs exactly (round-4 verdict: a 256-cubed
        # timing divided by a 1024-cubed nominal is not a speedup).
        # Smallest-first + per-rung flush; the 1e8 rung may not finish
        # inside the orchestrator budget, but a long-budget standalone
        # run commits it to BASELINE_CPU.json for later rounds.
        note("NOT on TPU (platform=%s) — CPU same-config baseline "
             "ladder, results will be marked platform=cpu"
             % detail['probe'].get('platform'))
        ladder = [(128, 100_000), (256, 1_000_000), (512, 10_000_000),
                  (1024, 10_000_000)]
        if os.environ.get('BENCH_CPU_FULL'):
            # the 1e8 north-star rung takes tens of minutes on this
            # 1-core host and TWO workers (a fallen-back TPU worker +
            # the forced-CPU sibling) can be walking this ladder
            # concurrently — multi-GB fields each. Only a dedicated
            # long-budget baseline run (BENCH_CPU_FULL=1) attempts it.
            ladder.append((1024, 100_000_000))
    for Nmesh, Npart in ladder:
        detail['state'] = 'config_nmesh%d_npart%.0e' % (Nmesh, Npart)
        _flush_detail(detail)
        try:
            res = run_config(
                Nmesh, Npart,
                method=best_big if Nmesh >= 512 else best_small)
            detail['configs'].append(res)
            _cache_tpu_result(res)
            _cache_cpu_baseline(res)
            note("ok: %s" % res)
        except Preempted:
            # SIGTERM'd mid-ladder: the rung's per-rep checkpoint is
            # already sealed — record the interruption and get out
            # inside the grace budget (relaunch resumes this rung)
            detail['state'] = 'preempted'
            detail['preempted'] = True
            detail['done'] = False
            _flush_detail(detail)
            note("preempted at Nmesh=%d Npart=%d — exiting within "
                 "grace budget" % (Nmesh, Npart))
            raise
        except Exception as e:
            detail['configs'].append({
                "metric": "fftpower_nmesh%d_npart%.0e" % (Nmesh, Npart),
                "error": str(e)[:300]})
            note("config Nmesh=%d Npart=%d failed: %s"
                 % (Nmesh, Npart, str(e)[:200]))
            _flush_detail(detail)
            continue  # a larger rung may still work (different failure
            # modes: staged fallback, smaller particle temporaries)
        _flush_detail(detail)

    # bonus pass (TPU only): now that the safe ladder is cached, try
    # the alternative paint kernels at scale; if one beats scatter,
    # re-measure the big rungs with it (the cache keeps the fastest
    # same-config record)
    if on_tpu:
        detail['state'] = 'tune512'
        _flush_detail(detail)
        best_big = tune(512, 10_000_000)
        detail['paint_method']['tune512_winner'] = best_big
        note("512-scale winner: %s" % best_big)
        if best_big != 'scatter':
            for Nmesh, Npart in [(512, 10_000_000), (1024, 10_000_000),
                                 (1024, 100_000_000)]:
                if best_big == 'sort' and Npart >= 50_000_000:
                    continue  # run_config's HBM override would revert
                    # to scatter — an expensive exact repeat
                detail['state'] = 'bonus_nmesh%d_%s' % (Nmesh, best_big)
                _flush_detail(detail)
                try:
                    res = run_config(Nmesh, Npart, method=best_big)
                    detail['configs'].append(res)
                    _cache_tpu_result(res)
                    # per-record paint_method already names the kernel;
                    # only a SUCCESSFUL bonus run updates the summary
                    detail['paint_method']['big'] = best_big
                    note("bonus ok: %s" % res)
                except Exception as e:
                    note("bonus Nmesh=%d (%s) failed: %s"
                         % (Nmesh, best_big, str(e)[:200]))
                _flush_detail(detail)

    # survey-path proof (acceptance config #5 at reduced scale): a
    # ConvolvedFFTPower run on whatever platform we have. Kept OUT of
    # detail['configs'] so the headline selection (largest fftpower
    # rung) and the 'TPU number landed' check are not hijacked; cached
    # under its own metric key. Same Nmesh on both platforms so the
    # vs_baseline lookup is same-config.
    detail['state'] = 'fkp'
    _flush_detail(detail)
    try:
        res = run_fkp(512)
        _attach_baseline(res)
        detail['fkp'] = res
        _cache_tpu_result(res)
        _cache_cpu_baseline(res)
        note("fkp ok: %s" % res)
    except Exception as e:
        detail['fkp'] = {"metric": "convpower_wallclock_nmesh512",
                         "error": str(e)[:300]}
        note("fkp failed: %s" % str(e)[:200])

    # irregular-primitive rates (diagnostic for the paint-kernel
    # ranking; small safe programs)
    detail['state'] = 'prim'
    _flush_detail(detail)
    try:
        detail['prim'] = run_prim(10_000_000)
        note("prim ok: %s" % detail['prim'])
    except Exception as e:
        detail['prim'] = {"error": str(e)[:300]}
        note("prim failed: %s" % str(e)[:200])

    detail['state'] = 'done'
    detail['done'] = True
    detail['total_s'] = round(time.time() - detail['t0'], 1)
    _flush_detail(detail)
    note("worker done in %.0fs" % detail['total_s'])
    return 0


# ---------------------------------------------------------------------------
# orchestrator (no jax in this process; never kills anything)

def _best_from_detail(detail, tpu_only=False):
    best = None
    for rec in detail.get('configs', []):
        if rec and rec.get('value', None) and rec.get('value', -1) > 0:
            if tpu_only and rec.get('platform') not in TPU_PLATFORMS:
                continue
            best = rec
    return best


def main():
    deadline = time.time() + TOTAL_BUDGET_S
    # reset the detail files so we never report a previous round's data
    _flush_detail({"state": "spawning", "configs": [], "done": False})

    log = open(WORKER_LOG, 'w')
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), '--worker'],
        stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True)  # detached: survives our exit/signals
    print("[bench] worker pid %d (detached; will never be killed)"
          % proc.pid, file=sys.stderr)

    # a second, forced-CPU worker in parallel: when the axon tunnel is
    # in its hang-25-minutes-then-fail mode the TPU worker can burn the
    # whole budget inside backend init, and a clearly-marked CPU number
    # is still better than value=-1 (it exercises the identical fused
    # pipeline). Separate detail file; merged lowest-preference below.
    cpu_env = dict(os.environ, JAX_PLATFORMS='cpu',
                   BENCH_DETAIL_PATH=CPU_DETAIL_PATH,
                   BENCH_WORKER_LOG=WORKER_LOG + '.cpu')
    cpu_env.pop('XLA_FLAGS', None)
    try:
        with open(CPU_DETAIL_PATH, 'w') as f:
            json.dump({"state": "spawning", "configs": [],
                       "done": False}, f)
        cpu_log = open(WORKER_LOG + '.cpu', 'w')
        cpu_proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), '--worker'],
            stdout=cpu_log, stderr=subprocess.STDOUT, env=cpu_env,
            start_new_session=True)
        print("[bench] cpu fallback worker pid %d" % cpu_proc.pid,
              file=sys.stderr)
    except Exception as e:
        cpu_proc = None
        print("[bench] cpu fallback worker failed to spawn: %s" % e,
              file=sys.stderr)

    def read(path):
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    state = {}
    while time.time() < deadline:
        state = read(DETAIL_PATH)
        tpu_over = proc.poll() is not None or state.get('done')
        if tpu_over:
            # if the TPU worker produced nothing, hold out for the
            # CPU fallback worker before reporting
            got_tpu = _best_from_detail(state, tpu_only=True)
            cpu_state = read(CPU_DETAIL_PATH)
            cpu_over = (cpu_proc is None
                        or cpu_proc.poll() is not None
                        or cpu_state.get('done'))
            if got_tpu or cpu_over:
                break
        time.sleep(5)

    state = read(DETAIL_PATH)
    if cpu_proc is not None:
        # fold THIS run's CPU-worker configs in as additional
        # candidates (platform-tagged, so TPU preference is
        # unaffected); when the spawn failed the stale file from a
        # previous run must not leak in
        cpu_state = read(CPU_DETAIL_PATH)
        state.setdefault('configs', []).extend(
            cpu_state.get('configs', []))
        if cpu_proc.poll() is None and \
                _best_from_detail(state, tpu_only=True):
            # a real TPU number landed: the CPU fallback is moot.
            # Unlike TPU work, a JAX_PLATFORMS=cpu child is safe to
            # terminate (no axon tunnel state to wedge).
            try:
                cpu_proc.terminate()
            except OSError:
                pass

    # preference order: live TPU result > cached TPU result from
    # earlier in the round > live CPU fallback (clearly marked) > -1
    best = _best_from_detail(state, tpu_only=True)
    if best is not None:
        out = {k: best.get(k) for k in ("metric", "value", "unit",
                                        "vs_baseline")}
        out['platform'] = best.get('platform')
        if best.get('baseline_source'):
            out['baseline_source'] = best['baseline_source']
        if not state.get('done'):
            out['note'] = ('budget elapsed at state=%s; worker left '
                           'running, larger configs may still land in '
                           'BENCH_DETAIL.json'
                           % state.get('state', '?'))
        print(json.dumps(out))
        return 0

    cached = _best_cached_tpu()
    if cached is not None:
        _attach_baseline(cached)
        out = {k: cached.get(k) for k in ("metric", "value", "unit",
                                          "vs_baseline")}
        out['platform'] = cached.get('platform')
        if cached.get('baseline_source'):
            out['baseline_source'] = cached['baseline_source']
        # a replay is marked as such in machine-readable form: the
        # regression tracker verdicts any replay older than its stale
        # bar, and the counter makes replays visible in the end-of-run
        # report — round 5 shipped a 4-day-old cache number silently
        out['measured_at'] = cached.get('measured_at')
        from nbodykit_tpu.diagnostics import counter
        from nbodykit_tpu.diagnostics.regress import parse_utc
        ts = parse_utc(cached.get('measured_at'))
        if ts is not None:
            out['cache_age_hours'] = round((time.time() - ts) / 3600.0,
                                           1)
        counter('bench.cache_replay').add(1)
        out['note'] = ('live TPU run unavailable this invocation '
                       '(worker state: %s); reporting the most recent '
                       'real-TPU measurement, taken at %s UTC '
                       '(BENCH_TPU_CACHE.json — possibly from an '
                       'earlier round if the tunnel was down all of '
                       'this one)'
                       % (state.get('state', '?'),
                          cached.get('measured_at')))
        print(json.dumps(out))
        return 0

    best = _best_from_detail(state)
    if best is not None:
        out = {k: best.get(k) for k in ("metric", "value", "unit",
                                        "vs_baseline")}
        out['platform'] = best.get('platform')
        out['note'] = ('CPU FALLBACK — the axon tunnel was wedged, so '
                       'this is NOT a TPU number; do not compare '
                       'against the baseline')
        print(json.dumps(out))
        return 0

    why = state.get('state', 'no state file')
    print(json.dumps({
        "metric": "fftpower_wallclock", "value": -1, "unit": "s",
        "vs_baseline": 0,
        "error": "no config completed (worker state: %s). The worker "
                 "was NOT killed; if state is 'probing' the axon "
                 "tunnel is wedged (see BENCH_WORKER.log)" % why}))
    return 1


if __name__ == '__main__':
    argv = _parse_fft_flags(sys.argv[1:])
    if not argv:
        sys.exit(main())
    # SIGTERM (preemption notice) gets a grace budget to finish the
    # current rep, checkpoint, and exit PREEMPTED_EXIT — the relaunch
    # resumes with zero recomputed reps (nbodykit_tpu.resilience.fleet)
    from nbodykit_tpu.resilience import (PREEMPTED_EXIT, Preempted,
                                         install_preemption_handler)
    install_preemption_handler(grace_s=float(
        os.environ.get('BENCH_PREEMPT_GRACE_S', '30') or 30))
    if argv[0] == '--worker':
        try:
            sys.exit(cmd_worker())
        except Preempted:
            sys.exit(PREEMPTED_EXIT)
    if argv[0] == '--config':
        # BENCH_REPS / BENCH_PHASES: the fault-injected resume smoke
        # (scripts/smoke.sh, tests/test_resilience.py) runs a tiny
        # 2-rep config with the phase split off
        try:
            print(json.dumps(run_config(
                int(argv[1]), int(argv[2]), *(argv[3:4] or ['scatter']),
                reps=int(os.environ.get('BENCH_REPS', '2') or 2),
                phases=os.environ.get('BENCH_PHASES', '1') != '0')))
        except Preempted as e:
            print(json.dumps({'preempted': True, 'detail': str(e)}))
            sys.exit(PREEMPTED_EXIT)
        sys.exit(0)
    if argv[0] == '--fftbw':
        print(json.dumps(run_fftbw(int(argv[1]) if argv[1:] else 512)))
        sys.exit(0)
    if argv[0] == '--fft-decomp-compare':
        print(json.dumps(run_fft_decomp(
            int(argv[1]) if argv[1:] else 256,
            reps=int(argv[2]) if argv[2:] else 3)))
        sys.exit(0)
    if argv[0] == '--prim':
        print(json.dumps(run_prim(int(argv[1]) if argv[1:]
                                  else 10_000_000)))
        sys.exit(0)
    if argv[0] == '--fkp':
        res = run_fkp(int(argv[1]) if argv[1:] else 512)
        _attach_baseline(res)
        _cache_tpu_result(res)
        _cache_cpu_baseline(res)
        print(json.dumps(res))
        sys.exit(0)
    if argv[0] == '--paint':
        print(json.dumps(run_paint(int(argv[1]), int(argv[2]),
                                   *(argv[3:4] or ['scatter']))))
        sys.exit(0)
    if argv[0] == '--paint-all':
        print(json.dumps(run_paint_all(
            int(argv[1]), int(argv[2]),
            reps=int(argv[3]) if argv[3:] else 3)))
        sys.exit(0)
    if argv[0] == '--serve-trace':
        print(json.dumps(run_serve_trace(
            int(argv[1]) if argv[1:] else 1000,
            per_task=int(argv[2]) if argv[2:] else 1,
            max_batch=int(argv[3]) if argv[3:] else 8,
            seed=int(argv[4]) if argv[4:] else 0)))
        sys.exit(0)
    if argv[0] == '--region-trace':
        print(json.dumps(run_region_trace(
            int(argv[1]) if argv[1:] else 200,
            fleets=int(argv[2]) if argv[2:] else 2,
            per_task=int(argv[3]) if argv[3:] else 1,
            seed=int(argv[4]) if argv[4:] else 0,
            interarrival_s=float(argv[5]) if argv[5:] else 0.0)))
        sys.exit(0)
    if argv[0] == '--integrity':
        print(json.dumps(run_integrity(
            int(argv[1]) if argv[1:] else 64,
            npart=int(argv[2]) if argv[2:] else 200000,
            reps=int(argv[3]) if argv[3:] else 3,
            seed=int(argv[4]) if argv[4:] else 7)))
        sys.exit(0)
    if argv[0] == '--ingest':
        print(json.dumps(run_ingest(
            int(argv[1]) if argv[1:] else 400000,
            nmesh=int(argv[2]) if argv[2:] else 64,
            chunk_rows=int(argv[3]) if argv[3:] else None,
            seed=int(argv[4]) if argv[4:] else 0)))
        sys.exit(0)
    if argv[0] == '--forward':
        print(json.dumps(run_forward(
            int(argv[1]) if argv[1:] else 32,
            npart=int(argv[2]) if argv[2:] else None,
            steps=int(argv[3]) if argv[3:] else 2,
            seed=int(argv[4]) if argv[4:] else 0)))
        sys.exit(0)
    if argv[0] == '--bispectrum':
        print(json.dumps(run_bispectrum(
            int(argv[1]) if argv[1:] else 32,
            npart=int(argv[2]) if argv[2:] else 20000,
            nbins=int(argv[3]) if argv[3:] else 3,
            seed=int(argv[4]) if argv[4:] else 0)))
        sys.exit(0)
    print("unknown args: %r" % (argv,), file=sys.stderr)
    sys.exit(2)
